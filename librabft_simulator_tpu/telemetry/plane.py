"""In-graph telemetry: the metrics plane and its static slot registry.

The whole simulator is traced to XLA, so observability must itself be
fixed-shape in-graph state with zero host sync in the hot loop.  This module
applies ``core/packing.py``'s slot-map idiom to *metrics*: every counter,
high-water mark, and histogram bucket of one instance lives in one flat
``[M]`` int32 plane with a static (name -> offset) registry, and every
update lowers to fusion-friendly elementwise forms:

* counters bump via constant one-hot adds (``arange(M) == off`` folds at
  compile time);
* histogram buckets bump via small one-hot compares against a dynamic
  offset;
* high-water regions update via static-offset dynamic-slice / update-slice.

No scalar scatters anywhere — the axon TPU stack miscompiles vmapped scalar
scatters at fleet batch sizes (utils/xops.py), and telemetry must never be
able to corrupt the run it observes.

The flight recorder is a separate ``[K, FR_COLS]`` ring per instance
(generalizing the round-switch ``trace_*`` ring): one row per processed
event — (kind, actor, global time, actor's post-update round, queue depth)
— with its running count stored in the plane's ``fr_count`` slot.  A fuzz
divergence or on-chip anomaly thus yields a replayable tail instead of a
bisection session (see scripts/fuzz_parity.py's minidump path).

Everything is gated by the static ``SimParams.telemetry`` flag; disabled,
the plane and ring are zero-width arrays and every update site is skipped
at trace time, so the compiled graph is identical to a telemetry-free
build (pinned by tests/test_telemetry.py + the kernel-census CI gate).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import quantile

I32 = jnp.int32

# Flight-recorder row layout.
FR_KIND = 0    # event kind (KIND_* incl. timer)
FR_ACTOR = 1   # handling node
FR_TIME = 2    # global clock of the event
FR_ROUND = 3   # actor's current_round after the update
FR_DEPTH = 4   # queue/inbox occupancy after the step's writes
FR_COLS = 5
FR_NAMES = ("kind", "actor", "time", "round", "depth")

# Slot aggregation kinds (how batched planes merge on the host).
SUM = "sum"    # counters and histogram buckets: add across instances
MAX = "max"    # high-water marks: max across instances


@functools.lru_cache(maxsize=None)
def registry(p_structural):
    """Static slot registry for one instance's metrics plane.

    Returns ``(slots, width)``: ``slots`` is a name-keyed dict of
    ``(offset, size, agg)`` and ``width`` the total plane length M.  Keyed
    on ``SimParams.structural()`` like core/packing.py's slot map; the
    layout depends only on n_nodes (per-node depth region) and the
    histogram width."""
    n = p_structural.n_nodes
    hb = quantile.HIST_BUCKETS
    order = [
        # Per-event-kind counters (live processed events; sum == n_events).
        ("ev_notify", 1, SUM),
        ("ev_request", 1, SUM),
        ("ev_response", 1, SUM),
        ("ev_timer", 1, SUM),
        # Loss / anomaly tallies.
        ("drops", 1, SUM),          # network drops (== n_msgs_dropped)
        ("overflow", 1, SUM),       # queue/inbox overflow (== n_queue_full)
        ("sync_jumps", 1, SUM),     # state-sync jumps across the fleet
        # Queue pressure high-water marks (post-step occupancy).
        ("queue_hwm", 1, MAX),          # total in-flight messages
        ("node_depth_hwm", n, MAX),     # per-receiver depth
        # Latency histograms (geometric buckets, utils/quantile.py).
        ("round_lat_hist", hb, SUM),    # time spent in a round at switch
        ("commit_lat_hist", hb, SUM),   # proposal -> commit, global time
        ("commit_lat_miss", 1, SUM),    # commits whose block left the window
        # Flight-recorder running count (ring lives in SimState.flight).
        ("fr_count", 1, SUM),
        # Parallel (lane) engine window health; zero under the serial engine.
        ("windows", 1, SUM),        # conservative windows processed
        ("horizon_stall", 1, SUM),  # nodes with work beyond the hz horizon
        ("lane_spill", 1, SUM),     # qualifying nodes beyond the A lanes
    ]
    slots = {}
    off = 0
    for name, size, agg in order:
        slots[name] = (off, size, agg)
        off += size
    return slots, off


def width(p) -> int:
    """Plane length M for these params (0 when telemetry is off)."""
    if not p.telemetry:
        return 0
    return registry(p.structural())[1]


def slot(p, name: str) -> tuple[int, int]:
    """(offset, size) of a named slot — static Python ints."""
    off, size, _ = registry(p.structural())[0][name]
    return off, size


def init_plane(p, shape=()):
    """Zero plane ([M] per instance; [0] when telemetry is off)."""
    return jnp.zeros(shape + (width(p),), I32)


def init_flight(p, shape=()):
    """Zero flight ring ([K, FR_COLS] per instance; K=0 when off)."""
    k = p.flight_cap if p.telemetry else 0
    return jnp.zeros(shape + (k, FR_COLS), I32)


# ---------------------------------------------------------------------------
# Device-side plane updates.  All take and return the [M] plane.
# ---------------------------------------------------------------------------


def bump(p, metrics, name: str, inc=1, when=None):
    """Add ``inc`` to a size-1 slot (masked by ``when``): one-hot add with a
    compile-time-constant mask."""
    off, size = slot(p, name)
    assert size == 1, name
    inc = jnp.asarray(inc, I32)
    if when is not None:
        inc = jnp.where(when, inc, 0)
    return metrics + jnp.where(jnp.arange(metrics.shape[-1]) == off, inc, 0)


def bump_hist(p, metrics, name: str, samples, mask):
    """Accumulate latency ``samples`` ([L] int32, masked by ``mask`` [L])
    into a histogram region: per-sample geometric bucket, one-hot compare
    against the (dynamic) bucket offsets, summed — no scatter."""
    off, size = slot(p, name)
    edges = jnp.asarray(quantile.histogram_edges(size))
    b = jnp.sum(samples[:, None] >= edges[None, :], axis=1).astype(I32)
    pos = off + jnp.clip(b, 0, size - 1)
    onehot = (jnp.arange(metrics.shape[-1])[None, :] == pos[:, None]) \
        & mask[:, None]
    return metrics + jnp.sum(onehot.astype(I32), axis=0)


def region_max(p, metrics, name: str, values):
    """Elementwise max of a region against ``values`` ([size] int32):
    static-offset slice / update-slice — the high-water-mark update."""
    off, size = slot(p, name)
    values = jnp.broadcast_to(jnp.asarray(values, I32), (size,))
    cur = jax.lax.dynamic_slice(metrics, (off,), (size,))
    return jax.lax.dynamic_update_slice(
        metrics, jnp.maximum(cur, values), (off,))


def read(p, metrics, name: str):
    """Scalar read of a size-1 slot (static index)."""
    off, size = slot(p, name)
    assert size == 1, name
    return metrics[off]


def commit_latency(p, store, ctx, startup, clock):
    """(found, latency) of the newest committed entry of one node.

    The committed log records (round, depth, state tag) but not times; the
    proposal time is recovered from the block table while the block is
    still inside the round window: global proposal time = the block's
    ``time`` (proposer-local) + the proposer's startup offset.  ``found``
    is False when the block has rotated out (or the store was rebuilt by
    an epoch switch / sync jump) — callers tally that as ``commit_lat_miss``
    rather than guessing.  Variant ties (Byzantine equivocation at the
    committed round) resolve to the lowest valid variant; the oracle
    mirrors this exactly (oracle/sim.py), so the histograms stay
    bit-comparable."""
    pos = jnp.remainder(ctx.commit_count - 1, p.commit_log)
    r_c = ctx.log_round[pos]
    sl = jnp.remainder(r_c, p.window)
    cand = store.blk_valid[sl] & (store.blk_round[sl] == r_c)
    found = jnp.any(cand)
    v = jnp.argmax(cand)  # lowest valid variant
    author = jnp.clip(store.blk_author[sl, v], 0, p.n_nodes - 1)
    t_prop = store.blk_time[sl, v] + startup[author]
    return found, jnp.maximum(clock - t_prop, 0)


def ring_order(count: int, cap: int) -> list:
    """Chronological storage indices of a capacity-``cap`` append ring after
    ``count`` appends, oldest surviving entry first.

    Shared by every ring decoder (the flight recorder here, the round-switch
    trace in analysis/data_writer.py): after overflow the oldest surviving
    entry sits at ``count % cap``, and reading in storage order would
    interleave stale and fresh entries.  An unused or disabled ring
    (``cap == 0``) decodes to no entries."""
    if cap <= 0:
        return []
    if count > cap:
        start = count % cap
        return [(start + i) % cap for i in range(cap)]
    return list(range(count))


def np_registry(p) -> dict:
    """Host view of the registry: name -> (offset, size, agg)."""
    return dict(registry(p.structural())[0])


def fold_planes(p, planes_np: np.ndarray, into=None) -> np.ndarray:
    """Reduce a ``[..., M]`` block of per-instance planes to one ``[M]``
    int64 partial — counters/histograms sum, high-water marks max —
    optionally folding into an existing partial.

    This is the associative shard-merge kernel of the fleet runtime: each
    dp shard's plane block folds independently on the host (telemetry/
    report.py walks ``addressable_shards``), so the full ``[B, M]`` fleet
    plane never has to land in one buffer.  All-zero (pre-halted padding)
    rows are absorbing for both aggregations, which is what makes padded
    fleets report identically to unpadded ones."""
    w = np_width(p)
    out = np.zeros((w,), np.int64) if into is None else into
    flat = np.asarray(planes_np, np.int64).reshape(-1, w) \
        if w else np.zeros((0, 0), np.int64)
    if flat.shape[0] == 0:
        return out
    for name, (off, size, agg) in np_registry(p).items():
        blk = flat[:, off:off + size]
        if agg == MAX:
            out[off:off + size] = np.maximum(out[off:off + size],
                                             blk.max(axis=0))
        else:
            out[off:off + size] += blk.sum(axis=0)
    return out


def np_width(p) -> int:
    return int(registry(p.structural())[1])


def decode(p, metrics_np: np.ndarray) -> dict:
    """One instance's plane -> {name: int | list}."""
    out = {}
    for name, (off, size, _) in np_registry(p).items():
        vals = metrics_np[off:off + size]
        out[name] = int(vals[0]) if size == 1 else [int(v) for v in vals]
    return out
