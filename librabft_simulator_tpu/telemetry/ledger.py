"""Runtime ledger: host-side span tracing with compile/dispatch/poll
attribution for the fleet runtime.

Every observability layer so far lives *in-graph* (the metrics plane, the
watchdog, the [D] digest stream) — but the costs that bind the project
today are *host-side*: tier-1 "fails" only by burning its wall-clock
budget on XLA compiles, and the pipelined fleet loop's double-buffering
claim (dispatch of chunk k+1 overlaps the poll of chunk k) had been
constructed, never measured.  This module is the host twin of the digest
stream: a process-wide, strictly host-only ledger of what the *host*
spent its time on, with zero traced ops — the engine graphs, census
budgets, and graph-audit signatures are exactly unchanged whether the
ledger records or not (pinned by tests/test_audit.py), so it works today
on CPU with the TPU tunnel down and becomes the merge target for on-chip
profiler captures when it revives.

Three pieces:

* **Spans** — :meth:`RuntimeLedger.span` is a context manager recording
  ``(kind, t0, dur)`` on the process ledger with monotonic-clock
  timestamps, thread-safe accumulation, and nesting (parent/depth come
  from a per-thread stack).  The taxonomy the runtime uses:
  ``compile`` (first call of a new executable — trace + XLA compile +
  first chunk), ``dispatch`` (enqueue of one chunk), ``poll`` (the
  blocking per-chunk digest fetch), ``host_merge`` (post-run host-side
  folds), ``run`` (a timed host section, e.g. a sweep config).  Chunked
  spans carry ``run=<id>``/``chunk=<i>`` attrs so one process can hold
  many loops without mixing their timelines.

* **Compile ledger** — every executable build is recorded keyed on a
  stable hash of ``SimParams.structural()`` plus the argument shapes
  (:func:`wrap_compile`), with the TRUE backend compile seconds and the
  persistent-cache hit/miss verdict taken from ``jax.monitoring`` events
  (``/jax/core/compile/backend_compile_duration``,
  ``/jax/compilation_cache/cache_{hits,misses}``) — not wall-clock
  guesswork.  Builds outside any attribution context (e.g. a test
  jitting directly) accumulate in an ``unattributed`` tally instead of
  vanishing.

* **Exports** — NDJSON streaming (``LIBRABFT_LEDGER_OUT``; rows are
  flushed as they are recorded, so a ``timeout``-killed process still
  leaves a usable partial file — readers tolerate a mid-write trailing
  line) followable by ``scripts/fleet_watch.py --ledger``; a
  Chrome-trace/Perfetto JSON exporter (:meth:`RuntimeLedger.to_perfetto`)
  so host spans can be overlaid on ``jax.profiler`` device traces via
  the existing ``librabft/*`` named scopes; and
  :func:`pipeline_stats` — the measured **pipeline-overlap fraction**
  and dispatch-queue bubble flags of the double-buffered fleet loop,
  plus the ``time_to_first_chunk`` headline the ROADMAP's AOT
  compile-cache item will be judged against.

CLI (no jax import — safe anywhere)::

    python -m librabft_simulator_tpu.telemetry.ledger \
        --attribution /tmp/_t1_ledger.ndjson --out attribution.json

summarizes a streamed ledger file into a compile-vs-run wall-time
attribution block (scripts/ci_tier1.sh runs this after the tier-1 suite).
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import os
import sys
import threading
import time

#: Schema version of the NDJSON rows / Perfetto export; readers refuse a
#: mismatch (the stream-registry discipline of telemetry/stream.py).
#: Single-sourced from the schema version table (telemetry/schema.py).
from . import schema  # noqa: E402

LEDGER_VERSION = schema.LEDGER_VERSION

#: Env knob: stream the process ledger as NDJSON to this path (rows are
#: flushed as recorded; a summary row lands on clean close).
OUT_ENV = "LIBRABFT_LEDGER_OUT"

# The span taxonomy (conventions — any string is a legal kind).
COMPILE = "compile"
DISPATCH = "dispatch"
POLL = "poll"
HOST_MERGE = "host_merge"
RUN = "run"
# Resident fleet service (serve/service.py): installing admitted scenario
# rows into halted slots (one batched donated device write per admission
# batch) and landing a finished slot's results on host.  Spans carry
# request ids, so per-request latency (submit->admit->first-chunk->egress)
# is reconstructible from the stream.
ADMIT = "admit"
EGRESS = "egress"
# Distributed bootstrap (distributed/bootstrap.py): the barrier inside
# jax.distributed.initialize.  All processes leave the coordinator
# handshake at (nearly) the same wall instant, so the span's END is the
# per-host clock-offset anchor the observatory's cross-host trace merge
# aligns ledgers on (each process's ledger epoch starts at its own
# perf_counter zero — incomparable across hosts without this anchor).
HANDSHAKE = "handshake"

#: A poll that returns faster than this means the chunk's digest was
#: already sitting on host when the loop got to it: the device finished
#: and idled while the host was still dispatching — a dispatch-queue
#: bubble (host-bound chunk), the exact failure mode the double-buffered
#: loop exists to avoid.
BUBBLE_FLOOR_S = 1e-4

# jax.monitoring events folded into compile-ledger entries.  Durations
# accumulate into the named field; count events tally.
_DURATION_EVENTS = {
    "/jax/core/compile/jaxpr_trace_duration": "trace_s",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "lower_s",
    "/jax/core/compile/backend_compile_duration": "compile_s",
    "/jax/compilation_cache/cache_retrieval_time_sec": "cache_retrieve_s",
}
_COUNT_EVENTS = {
    "/jax/compilation_cache/cache_hits": "cache_hits",
    "/jax/compilation_cache/cache_misses": "cache_misses",
}


@dataclasses.dataclass
class Span:
    seq: int
    kind: str
    t0_s: float                    # offset from the ledger epoch
    dur_s: float = 0.0
    thread: int = 0
    parent: int | None = None      # seq of the enclosing span, same thread
    depth: int = 0
    attrs: dict = dataclasses.field(default_factory=dict)

    def to_json(self) -> dict:
        return {"kind": "span", "seq": self.seq, "name": self.kind,
                "t0_s": round(self.t0_s, 6), "dur_s": round(self.dur_s, 6),
                "thread": self.thread, "parent": self.parent,
                "depth": self.depth, **self.attrs}


def params_key(p) -> str:
    """Stable short key for a structural-params object: sha1 prefix of its
    repr.  Two params with equal ``structural()`` — i.e. one compiled
    executable — share one key; the full repr rides in the compile-ledger
    entry once, so rows stay small without losing the mapping."""
    return hashlib.sha1(repr(p).encode()).hexdigest()[:12]


class RuntimeLedger:
    """Thread-safe host-side span + compile ledger.

    ``clock`` defaults to ``time.perf_counter`` (monotonic); tests inject
    a fake for deterministic output.  ``enabled=False`` stops
    accumulation but spans still *time* (callers read ``sp.dur_s`` for
    their own reporting), so disabling the ledger never changes observed
    values.  ``max_spans`` bounds memory on pathological span counts —
    overflow increments ``dropped`` instead of growing without limit."""

    def __init__(self, clock=None, max_spans: int = 250_000, out=None,
                 meta: dict | None = None):
        self._clock = clock if clock is not None else time.perf_counter
        self._lock = threading.RLock()  # close() summarizes under the lock
        self._local = threading.local()
        self._seq = 0
        self._run_seq = 0
        self.enabled = True
        self.max_spans = max_spans
        self.spans: list[Span] = []
        self.dropped = 0
        self.compiles: list[dict] = []   # the compile ledger, append order
        self._compile_seen: set = set()
        self.unattributed: dict = {}     # event -> [count, total_s]
        self._out = None
        self._owns_out = False
        self.epoch = self._clock()
        if out is not None:
            self.open_out(out, meta)

    # -- clock ----------------------------------------------------------

    def now(self) -> float:
        """Seconds since the ledger epoch (monotonic clock)."""
        return self._clock() - self.epoch

    # -- NDJSON streaming ----------------------------------------------

    def open_out(self, out, meta: dict | None = None) -> None:
        """Attach an NDJSON sink (path or file-like): a meta line goes out
        immediately, then every recorded span/compile row as it lands."""
        self._owns_out = isinstance(out, str)
        self._out = open(out, "w") if self._owns_out else out
        header = {"kind": "meta", "schema": "runtime_ledger",
                  "ledger_version": LEDGER_VERSION, "pid": os.getpid()}
        if meta:
            header.update(meta)
        self._emit(header)

    def _emit(self, obj: dict) -> None:
        if self._out is not None:
            self._out.write(json.dumps(obj) + "\n")
            self._out.flush()

    def close(self) -> None:
        """Emit the summary row and release an owned sink (also called at
        interpreter exit for the env-configured process ledger; a
        timeout-killed process skips this, leaving the streamed rows)."""
        with self._lock:
            self._emit({"kind": "summary", **self.summary()})
            if self._owns_out and self._out is not None:
                self._out.close()
            self._out = None

    # -- spans ----------------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    @contextlib.contextmanager
    def span(self, kind: str, **attrs):
        """Record one host span; yields the :class:`Span` so callers can
        read ``sp.dur_s`` after the block (the one timing source for
        wall-time reporting — no ad-hoc perf_counter pairs)."""
        stack = self._stack()
        parent = stack[-1] if stack else None
        with self._lock:
            seq = self._seq
            self._seq += 1
        sp = Span(seq=seq, kind=kind, t0_s=self.now(),
                  thread=threading.get_ident() & 0xFFFFFFFF,
                  parent=parent.seq if parent is not None else None,
                  depth=len(stack), attrs=attrs)
        stack.append(sp)
        try:
            yield sp
        finally:
            stack.pop()
            sp.dur_s = self.now() - sp.t0_s
            if self.enabled:
                with self._lock:
                    if len(self.spans) < self.max_spans:
                        self.spans.append(sp)
                        self._emit(sp.to_json())
                    else:
                        self.dropped += 1

    def new_run(self, label: str, **attrs) -> int:
        """A fresh run id for one host loop; chunk spans tagged with it
        stay separable from every other loop in the process (multiple
        run_to_completion / run_sharded calls share one ledger)."""
        with self._lock:
            self._run_seq += 1
            rid = self._run_seq
        if self.enabled:
            with self._lock:
                self._emit({"kind": "run", "run": rid, "label": label,
                            "t0_s": round(self.now(), 6), **attrs})
        return rid

    # -- compile ledger --------------------------------------------------

    def _compile_ctx(self):
        return getattr(self._local, "compile_ctx", None)

    def on_event(self, event: str, **kw) -> None:
        """jax.monitoring count-event sink (also the test entry point)."""
        field = _COUNT_EVENTS.get(event)
        if field is None:
            return
        ctx = self._compile_ctx()
        if ctx is not None:
            ctx[field] += 1
        else:
            with self._lock:
                tally = self.unattributed.setdefault(event, [0, 0.0])
                tally[0] += 1

    def on_event_duration(self, event: str, dur: float, **kw) -> None:
        """jax.monitoring duration-event sink (also the test entry
        point)."""
        field = _DURATION_EVENTS.get(event)
        if field is None:
            return
        ctx = self._compile_ctx()
        if ctx is not None:
            ctx[field] += float(dur)
        else:
            with self._lock:
                tally = self.unattributed.setdefault(event, [0, 0.0])
                tally[0] += 1
                tally[1] += float(dur)

    def annotate_compile(self, **kw) -> None:
        """Merge fields into the compile-ledger entry currently being
        attributed on this thread (no-op outside an attribution block).
        The AOT store (utils/aot.py) uses this to land its verdict —
        ``_aot='hit'/'stale'`` plus ``aot_load_s`` — on the entry the
        enclosing :meth:`compile_attribution` will classify."""
        ctx = self._compile_ctx()
        if ctx is not None:
            ctx.update(kw)

    @contextlib.contextmanager
    def compile_attribution(self, key: str, **meta):
        """Attribute every compile-class jax.monitoring event fired on
        this thread inside the block to one compile-ledger entry; the
        enclosing ``compile`` span times the whole first call (trace +
        compile + first chunk — ``first_call_s``), while ``compile_s`` is
        the true backend-compile time from the events."""
        entry = {"key": key, **meta, "trace_s": 0.0, "lower_s": 0.0,
                 "compile_s": 0.0, "cache_retrieve_s": 0.0,
                 "cache_hits": 0, "cache_misses": 0}
        prev = self._compile_ctx()
        self._local.compile_ctx = entry
        try:
            with self.span(COMPILE, key=key) as sp:
                yield entry
        finally:
            self._local.compile_ctx = prev
            entry["first_call_s"] = round(sp.dur_s, 6)
            for f in ("trace_s", "lower_s", "compile_s", "cache_retrieve_s"):
                entry[f] = round(entry[f], 6)
            if entry["cache_hits"] and not entry["cache_misses"]:
                entry["cache"] = "persistent-hit"
            elif entry["cache_misses"]:
                # A jaxlib/XLA upgrade invalidates every persistent-cache
                # entry by construction (compiler version is in the cache
                # key); the cache-dir stamp (utils/cache.py) makes that a
                # distinguishable verdict instead of a mystery cold run.
                from ..utils import cache as _cache

                entry["cache"] = ("stale-toolchain"
                                  if _cache.stale_toolchain() is not None
                                  else "persistent-miss")
            elif entry["compile_s"] > 0:
                entry["cache"] = "uncached"      # no persistent cache set up
            else:
                entry["cache"] = "memory"        # in-process executable reuse
            # AOT-store verdicts (utils/aot.py, via annotate_compile)
            # override: an aot-hit paid NO trace/lower/compile at all —
            # the entry's only cost is aot_load_s — and an aot-stale
            # entry fell back to whatever the base verdict says (kept in
            # ``fallback`` so the staleness is loud but the real cost
            # attribution survives).
            aot_note = entry.pop("_aot", None)
            if aot_note == "hit":
                entry["cache"] = "aot-hit"
            elif aot_note == "stale":
                entry["fallback"] = entry["cache"]
                entry["cache"] = "aot-stale"
            elif aot_note == "export":
                # Build step: a deliberate full fresh compile (the
                # persistent cache is bypassed — see utils/aot._export),
                # serialized into the store.
                entry["cache"] = "aot-export"
            if self.enabled:
                with self._lock:
                    self.compiles.append(entry)
                    self._emit({"kind": "compile", **entry})

    def seen_compile(self, token) -> bool:
        """Record-once guard for :func:`wrap_compile`: True if ``token``
        was already claimed (the executable's first call was already
        attributed)."""
        with self._lock:
            if token in self._compile_seen:
                return True
            self._compile_seen.add(token)
            return False

    # -- summaries -------------------------------------------------------

    def span_totals(self) -> dict:
        """{kind: {"count": n, "total_s": s}} over recorded spans."""
        out: dict = {}
        with self._lock:
            spans = list(self.spans)
        for sp in spans:
            row = out.setdefault(sp.kind, {"count": 0, "total_s": 0.0})
            row["count"] += 1
            row["total_s"] += sp.dur_s
        for row in out.values():
            row["total_s"] = round(row["total_s"], 6)
        return out

    def pipeline_stats(self, run: int | None = None) -> dict:
        """Measured pipeline health of one chunked host loop — see the
        module-level :func:`pipeline_stats` (this method feeds it the
        recorded spans)."""
        with self._lock:
            rows = [sp.to_json() for sp in self.spans]
        return pipeline_stats(rows, run=run)

    def ring_stats(self, run: int | None = None) -> dict | None:
        """Ring-dispatch health of one ``wrap="device"`` loop — see the
        module-level :func:`ring_stats`; ``None`` when the selected run
        recorded no ring polls (a host-wrap loop)."""
        with self._lock:
            rows = [sp.to_json() for sp in self.spans]
        return ring_stats(rows, run=run)

    def summary(self) -> dict:
        comp_s = sum(e["compile_s"] for e in self.compiles)
        return {
            "ledger_version": LEDGER_VERSION,
            "spans": self.span_totals(),
            "spans_dropped": self.dropped,
            "compile_entries": len(self.compiles),
            "compile_s_total": round(comp_s, 3),
            "persistent_cache": {
                "hits": sum(e["cache_hits"] for e in self.compiles),
                "misses": sum(e["cache_misses"] for e in self.compiles),
            },
            "aot": _aot_tally(self.compiles),
            "unattributed": {k: {"count": v[0], "total_s": round(v[1], 6)}
                             for k, v in self.unattributed.items()},
        }

    # -- Perfetto / Chrome trace export ---------------------------------

    def to_perfetto(self, path: str | None = None) -> dict:
        """Chrome-trace JSON ('X' complete events, µs timestamps) of the
        recorded spans.  Load in ui.perfetto.dev / chrome://tracing; the
        span names sit alongside the engines' ``librabft/*``
        ``jax.named_scope`` regions of a ``jax.profiler`` device trace,
        so host dispatch/poll activity can be read against on-chip kernel
        timelines once the tunnel revives (ROADMAP checklist item 10)."""
        with self._lock:
            spans = list(self.spans)
        events = [{
            "name": sp.kind,
            "cat": "librabft_host",
            "ph": "X",
            "ts": round(sp.t0_s * 1e6, 3),
            "dur": round(sp.dur_s * 1e6, 3),
            "pid": os.getpid(),
            "tid": sp.thread,
            "args": dict(sp.attrs, seq=sp.seq),
        } for sp in spans]
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"schema": "runtime_ledger",
                          "ledger_version": LEDGER_VERSION},
        }
        if path is not None:
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc


def _aot_tally(compiles) -> dict:
    """AOT-store verdict counts + total load seconds over compile-ledger
    entries (utils/aot.py wrote the fields; pure row math, jax-free)."""
    return {
        "hits": sum(1 for e in compiles if e.get("cache") == "aot-hit"),
        "stale": sum(1 for e in compiles if e.get("cache") == "aot-stale"),
        "load_s": round(sum(e.get("aot_load_s", 0.0) for e in compiles), 3),
    }


# ---------------------------------------------------------------------------
# Pipeline analysis (pure row-dict functions: fleet_watch --ledger and the
# CLI run these on loaded NDJSON with no jax anywhere near).
# ---------------------------------------------------------------------------


def pipeline_stats(rows, run: int | None = None,
                   bubble_floor_s: float = BUBBLE_FLOOR_S) -> dict:
    """The measured double-buffered-pipeline health of one chunked loop.

    Consumes span rows (dicts, as streamed/recorded) with ``name`` in
    {dispatch, poll} and a ``chunk`` attr; ``run=None`` picks the LAST
    run id present (the most recent loop).  Chunk 0 carries the cold
    compile, so steady-state aggregates exclude it.

    * ``overlap_fraction`` = poll_s / (poll_s + dispatch_s) over
      steady-state chunks: the fraction of the host loop spent blocked on
      the device *while the next chunk was already enqueued* — the
      overlap the double-buffered loop claims.  ~1.0 means the device is
      the bottleneck and dispatch is fully hidden; ~0 means the host
      (dispatch enqueue + record) is the bottleneck and the device idles
      between chunks.
    * ``bubbles`` — chunks whose poll returned in under
      ``bubble_floor_s``: the digest was already on host, i.e. the device
      finished and sat idle while the host was still busy — a
      dispatch-queue bubble.
    * ``time_to_first_chunk_s`` — first dispatch start to first poll end,
      cold compile included: the headline the AOT compile-cache ROADMAP
      item is judged against (jax/backend import time is outside the
      ledger epoch and excluded).
    """
    spans = [r for r in rows if r.get("kind") == "span"
             and r.get("name") in (DISPATCH, POLL) and "chunk" in r]
    if run is None:
        runs = [r.get("run") for r in spans if r.get("run") is not None]
        run = runs[-1] if runs else None
    if run is not None:
        spans = [r for r in spans if r.get("run") == run]
    chunks: dict = {}
    for r in spans:
        row = chunks.setdefault(int(r["chunk"]),
                                {"chunk": int(r["chunk"]),
                                 "dispatch_s": 0.0, "poll_s": 0.0})
        row[r["name"] + "_s"] = round(row[r["name"] + "_s"]
                                      + float(r["dur_s"]), 6)
    ordered = [chunks[c] for c in sorted(chunks)]
    out = {"run": run, "chunks": len(ordered), "rows": ordered}
    firsts_d = [r for r in spans if r["name"] == DISPATCH]
    firsts_p = [r for r in spans if r["name"] == POLL]
    if firsts_d and firsts_p:
        d0 = min(firsts_d, key=lambda r: r["t0_s"])
        p0 = min(firsts_p, key=lambda r: r["t0_s"])
        out["time_to_first_chunk_s"] = round(
            p0["t0_s"] + p0["dur_s"] - d0["t0_s"], 6)
    steady = [r for r in ordered if r["chunk"] > 0]
    polled = [r for r in steady if r["poll_s"] > 0 or r["dispatch_s"] > 0]
    dispatch_s = sum(r["dispatch_s"] for r in polled)
    poll_s = sum(r["poll_s"] for r in polled)
    out["dispatch_s"] = round(dispatch_s, 6)
    out["poll_s"] = round(poll_s, 6)
    out["overlap_fraction"] = (round(poll_s / (poll_s + dispatch_s), 4)
                               if poll_s + dispatch_s > 0 else None)
    bubbles = [r["chunk"] for r in polled if r["poll_s"] < bubble_floor_s]
    out["bubbles"] = bubbles
    out["bubble_count"] = len(bubbles)
    return out


def ring_stats(rows, run: int | None = None) -> dict | None:
    """Ring-dispatch health of one ``wrap="device"`` loop (pure row math,
    the :func:`pipeline_stats` twin for the in-graph chunk loop).

    Consumes the ring POLL spans parallel/sharded.run_sharded records —
    one per OUTER call, carrying ``retired`` (ring rows actually
    written) and ``cap`` (the dispatched chunk budget).  Returns ``None``
    when the selected run has no ring spans (a host-wrap ledger), so
    viewers can branch on presence.

    * ``retired_per_dispatch`` — mean chunks retired per outer call: the
      dispatch amortization the device wrap buys (up to ring_k).
    * ``polls_per_retired_chunk`` — outer calls / retired chunks: the
      headline, 1.0 on the host wrap, <= 1/ring_k here on non-halting
      horizons.
    * ``ring_full`` — outer calls that retired their full budget
      (``retired == cap``: no early exit).
    * ``early_exit`` — outer calls that stopped short of ``cap``: the
      all-halted predicate fired mid-ring.
    """
    spans = [r for r in rows if r.get("kind") == "span"
             and r.get("name") == POLL and "retired" in r]
    if run is None:
        runs = [r.get("run") for r in spans if r.get("run") is not None]
        run = runs[-1] if runs else None
    if run is not None:
        spans = [r for r in spans if r.get("run") == run]
    if not spans:
        return None
    retired = sum(int(r["retired"]) for r in spans)
    full = sum(1 for r in spans
               if "cap" in r and int(r["retired"]) >= int(r["cap"]))
    return {
        "run": run,
        "dispatches": len(spans),
        "retired_chunks": retired,
        "retired_per_dispatch": round(retired / len(spans), 4),
        "polls_per_retired_chunk": (round(len(spans) / retired, 4)
                                    if retired else None),
        "ring_full": full,
        "early_exit": len(spans) - full,
    }


def _run_seconds(spans) -> float:
    """Dispatched-work wall time with nesting double-counts removed.

    Spans overlap two ways: a ``compile`` span nests inside the cold
    chunk's ``dispatch`` span (the first call IS the compile), and a
    ``run`` section (sweep config, timed bench window) contains its
    loop's ``dispatch``/``poll`` spans.  So: count dispatch+poll, minus
    compile time nested inside them; count a ``run`` span only for its
    EXCLUSIVE time (its duration minus recorded dispatch/poll/compile
    descendants — a timed section whose loop records no inner spans
    still counts in full).  Parent links (same-thread nesting) are in
    the rows."""
    by_seq = {r["seq"]: r for r in spans if "seq" in r}

    def ancestors(r):
        seen = set()
        while r.get("parent") is not None and r["parent"] not in seen:
            seen.add(r["parent"])
            r = by_seq.get(r["parent"])
            if r is None:
                return
            yield r

    disp_poll = [r for r in spans if r.get("name") in (DISPATCH, POLL)]
    nested_compile = 0.0
    run_children: dict = {}
    for r in spans:
        if r.get("name") not in (DISPATCH, POLL, COMPILE):
            continue
        anc = list(ancestors(r))
        if r["name"] == COMPILE:
            if any(a.get("name") in (DISPATCH, POLL) for a in anc):
                # Covered by its enclosing dispatch: subtract once, and
                # do NOT also charge the RUN (the dispatch will).
                nested_compile += float(r["dur_s"])
                continue
        elif any(a.get("name") in (DISPATCH, POLL) for a in anc):
            continue  # nested dispatch/poll: outermost one accounts
        # Charge each outermost counted span to its nearest enclosing
        # RUN section once.
        for a in anc:
            if a.get("name") == RUN:
                run_children[a["seq"]] = (run_children.get(a["seq"], 0.0)
                                          + float(r["dur_s"]))
                break
    run_exclusive = sum(
        max(0.0, float(r["dur_s"]) - run_children.get(r.get("seq"), 0.0))
        for r in spans if r.get("name") == RUN)
    total = sum(float(r["dur_s"]) for r in disp_poll)
    return max(0.0, total - nested_compile) + run_exclusive


def compile_attribution_summary(rows, top: int = 10) -> dict:
    """Compile-vs-run wall-time attribution from loaded ledger rows: how
    much of the process went to XLA compiles (per structural key, with
    persistent-cache verdicts) vs dispatched work — the data behind the
    tier-1 cold-vs-warm dot gap."""
    compiles = [r for r in rows if r.get("kind") == "compile"]
    spans = [r for r in rows if r.get("kind") == "span"]
    span_totals: dict = {}
    for r in spans:
        t = span_totals.setdefault(r["name"], {"count": 0, "total_s": 0.0})
        t["count"] += 1
        t["total_s"] = round(t["total_s"] + float(r["dur_s"]), 6)
    compile_s = sum(e.get("compile_s", 0.0) for e in compiles)
    trace_s = sum(e.get("trace_s", 0.0) + e.get("lower_s", 0.0)
                  for e in compiles)
    first_call_s = sum(e.get("first_call_s", 0.0) for e in compiles)
    run_s = _run_seconds(spans)
    summaries = [r for r in rows if r.get("kind") == "summary"]
    unattributed = summaries[-1].get("unattributed", {}) if summaries else {}
    by_key: dict = {}
    for e in compiles:
        k = by_key.setdefault(e.get("key", "?"), {
            "key": e.get("key", "?"), "builds": 0, "compile_s": 0.0,
            "cache": {}, "meta": {kk: e[kk] for kk in ("engine", "n_nodes")
                                  if kk in e}})
        k["builds"] += 1
        k["compile_s"] = round(k["compile_s"] + e.get("compile_s", 0.0), 6)
        verdict = e.get("cache", "?")
        k["cache"][verdict] = k["cache"].get(verdict, 0) + 1
    ranked = sorted(by_key.values(), key=lambda k: -k["compile_s"])
    return {
        "ledger_version": LEDGER_VERSION,
        "compile": {
            "entries": len(compiles),
            "distinct_keys": len(by_key),
            "compile_s": round(compile_s, 3),
            "trace_lower_s": round(trace_s, 3),
            "first_call_s": round(first_call_s, 3),
            "persistent_cache": {
                "hits": sum(e.get("cache_hits", 0) for e in compiles),
                "misses": sum(e.get("cache_misses", 0) for e in compiles),
            },
            "aot": _aot_tally(compiles),
            "top": ranked[:top],
        },
        "spans": span_totals,
        "unattributed": unattributed,
        "compile_vs_run": {
            "compile_s": round(compile_s, 3),
            "run_s": round(run_s, 3),
            "compile_fraction": (round(compile_s / (compile_s + run_s), 4)
                                 if compile_s + run_s > 0 else None),
        },
    }


# ---------------------------------------------------------------------------
# NDJSON loading (tolerant of a mid-write trailing line).
# ---------------------------------------------------------------------------


def read_ndjson(path: str, tolerant: bool = True) -> list[dict]:
    """Parse an NDJSON file into row dicts.  ``tolerant`` (default)
    ignores an unparseable FINAL non-empty line — the mid-write tail of a
    live or timeout-killed writer; a corrupt line anywhere else still
    raises (that's damage, not liveness)."""
    with open(path) as f:
        lines = [ln.strip() for ln in f]
    lines = [ln for ln in lines if ln]
    rows = []
    for i, ln in enumerate(lines):
        try:
            rows.append(json.loads(ln))
        except ValueError:
            if tolerant and i == len(lines) - 1:
                break
            raise
    return rows


def load_ndjson(path: str) -> tuple[dict, list[dict]]:
    """Read a streamed ledger file back: ``(meta, rows)``.  Refuses a
    file from another :data:`LEDGER_VERSION` (or a non-ledger NDJSON)."""
    rows = read_ndjson(path)
    metas = [r for r in rows if r.get("kind") == "meta"]
    if not metas or metas[0].get("schema") != "runtime_ledger":
        raise ValueError(
            f"{path}: no runtime_ledger meta line; not a ledger NDJSON "
            "artifact (fleet digest streams are read by fleet_watch "
            "without --ledger)")
    meta = metas[0]
    schema.require_ledger_version(meta.get("ledger_version"), what=path)
    return meta, [r for r in rows if r.get("kind") != "meta"]


# ---------------------------------------------------------------------------
# The process ledger + jax.monitoring wiring.
# ---------------------------------------------------------------------------

_PROCESS: RuntimeLedger | None = None
_PROCESS_LOCK = threading.Lock()
_LISTENERS_ON = False


def get() -> RuntimeLedger:
    """The process-wide ledger (created on first use).  If
    ``LIBRABFT_LEDGER_OUT`` is set at creation time, rows stream there as
    NDJSON and a summary row lands at clean interpreter exit."""
    global _PROCESS
    if _PROCESS is None:
        with _PROCESS_LOCK:
            if _PROCESS is None:
                out = os.environ.get(OUT_ENV, "").strip() or None
                lg = RuntimeLedger(out=out, meta={"argv0": sys.argv[0]})
                if out:
                    import atexit

                    atexit.register(lg.close)
                _PROCESS = lg
    return _PROCESS


def reset(clock=None) -> RuntimeLedger:
    """Replace the process ledger (tests): a fresh in-memory ledger, no
    sink, optional injected clock."""
    global _PROCESS
    with _PROCESS_LOCK:
        _PROCESS = RuntimeLedger(clock=clock)
    return _PROCESS


def _ensure_listeners() -> None:
    """Register the jax.monitoring sinks once (lazy: this module must
    import cleanly in jax-free processes like fleet_watch — jax is only
    touched from code paths that already run under jax)."""
    global _LISTENERS_ON
    if _LISTENERS_ON:
        return
    with _PROCESS_LOCK:
        if _LISTENERS_ON:
            return
        from jax import monitoring

        monitoring.register_event_listener(
            lambda event, **kw: get().on_event(event, **kw))
        monitoring.register_event_duration_secs_listener(
            lambda event, dur, **kw: get().on_event_duration(event, dur, **kw))
        _LISTENERS_ON = True


def _shape_sig(args) -> str:
    """Cheap shape signature of a call's pytree args: leading leaf shape
    + leaf count.  Distinguishes the batch-size recompiles the engines
    actually see without hashing every aval."""
    import jax

    leaves = jax.tree_util.tree_leaves(args)
    if not leaves:
        return "()"
    return f"{tuple(getattr(leaves[0], 'shape', ()))}x{len(leaves)}"


def wrap_compile(call, key: str, **meta):
    """Wrap an executable's host entry point so its first call per
    argument-shape signature is recorded in the compile ledger (keyed on
    ``key`` — a :func:`params_key` of the structural params — plus the
    shapes), attributed via jax.monitoring.  Later calls pay one set
    lookup.  The wrapped callable is return-transparent."""
    _ensure_listeners()
    base = (key, tuple(sorted((k, str(v)) for k, v in meta.items())))

    def wrapped(*args):
        lg = get()
        sig = _shape_sig(args)
        if lg.seen_compile((base, sig)):
            return call(*args)
        with lg.compile_attribution(key, shapes=sig, **meta):
            return call(*args)

    # Keep the underlying executable's AOT surface reachable: consumers
    # like scripts/kernel_census.py drive `.lower(...).compile()` on the
    # engine runners directly (those paths bypass the ledger — they are
    # measurement tools, not dispatches).
    wrapped.__wrapped__ = call
    for attr in ("lower", "trace", "eval_shape"):
        if hasattr(call, attr):
            setattr(wrapped, attr, getattr(call, attr))
    return wrapped


# ---------------------------------------------------------------------------
# CLI: compile-vs-run attribution from a streamed ledger file.
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="Summarize a streamed runtime-ledger NDJSON file")
    ap.add_argument("--attribution", metavar="NDJSON", required=True,
                    help="ledger stream (LIBRABFT_LEDGER_OUT path)")
    ap.add_argument("--out", default=None,
                    help="write the attribution JSON here too")
    ap.add_argument("--perfetto", default=None, metavar="PATH",
                    help="additionally re-export the spans as a "
                         "Chrome-trace/Perfetto JSON")
    args = ap.parse_args(argv)
    try:
        meta, rows = load_ndjson(args.attribution)
    except (OSError, ValueError) as e:
        print(f"ledger: {e}", file=sys.stderr)
        return 1
    summary = compile_attribution_summary(rows)
    summary["source"] = args.attribution
    summary["pid"] = meta.get("pid")
    # The pipeline headline only makes sense for a DOUBLE-BUFFERED loop
    # (run rows carry pipeline=True: run_sharded / bench_fleet).  A
    # serial run_to_completion loop polls the chunk it just dispatched,
    # so its overlap fraction would read ~1.0 without meaning it —
    # omit the block rather than present a bogus number.
    pipelined = [r["run"] for r in rows
                 if r.get("kind") == "run" and r.get("pipeline")]
    pipe = pipeline_stats(rows, run=pipelined[-1]) if pipelined else None
    if pipe and pipe["chunks"]:
        summary["pipeline"] = {k: pipe[k] for k in
                               ("run", "chunks", "overlap_fraction",
                                "bubble_count", "time_to_first_chunk_s")
                               if k in pipe}
    if args.perfetto:
        spans = [r for r in rows if r.get("kind") == "span"]
        doc = {"traceEvents": [{
            "name": r["name"], "cat": "librabft_host", "ph": "X",
            "ts": round(float(r["t0_s"]) * 1e6, 3),
            "dur": round(float(r["dur_s"]) * 1e6, 3),
            "pid": meta.get("pid", 0), "tid": r.get("thread", 0),
            "args": {k: v for k, v in r.items()
                     if k not in ("kind", "name", "t0_s", "dur_s",
                                  "thread", "parent", "depth")},
        } for r in spans], "displayTimeUnit": "ms"}
        with open(args.perfetto, "w") as f:
            json.dump(doc, f)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=1)
    print(json.dumps(summary, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
