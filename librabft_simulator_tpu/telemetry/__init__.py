"""Zero-sync observability for the TPU fleet.

Three pieces, mirroring the split the rest of the codebase uses:

* :mod:`.plane` — the device side: a fixed-shape int32 metrics plane
  (``SimState.metrics``, one ``[M]`` vector per instance with a static slot
  registry, the ``core/packing.py`` idiom applied to counters) plus a
  last-K-events flight-recorder ring (``SimState.flight``, ``[K, 5]``).
  Everything is gated by the static ``SimParams.telemetry`` flag: disabled,
  the arrays are zero-width and every update compiles out, so the graph is
  bit- and kernel-identical to a telemetry-free build.
* :mod:`.report` — the host side: decode + merge metric planes, flight
  rings, and ``analysis/data_writer.py`` output into one run-report dict
  that ``bench.py`` and ``analysis/sweeps.py`` attach to their contract
  lines.
* :mod:`.stream` — the live side: a fixed ``[D]`` fleet-health digest
  riding the fleet loop's per-chunk halt poll (zero added host syncs), an
  in-graph consensus watchdog (``SimState.wd``, gated by the static
  ``SimParams.watchdog`` with the same zero-cost-off contract), and the
  host ``TimelineRecorder`` / NDJSON stream ``scripts/fleet_watch.py``
  follows live.  Slot maps are frozen behind ``REGISTRY_VERSION``.
* :mod:`.profiling` — ``jax.named_scope`` annotations around the step's
  phases so on-chip ``jax.profiler`` traces map to code regions.
* :mod:`.ledger` — the HOST side of the clock: a process-wide span
  tracer (compile / dispatch / poll / host_merge) with a compile ledger
  keyed on ``SimParams.structural()`` + shapes (true backend-compile
  seconds, persistent-cache hit/miss via ``jax.monitoring``), NDJSON
  streaming (``LIBRABFT_LEDGER_OUT``; ``fleet_watch.py --ledger``), a
  Perfetto exporter that overlays the ``librabft/*`` device scopes, and
  the measured pipeline-overlap / time-to-first-chunk numbers of the
  double-buffered fleet loop.  Strictly host-only: zero traced ops.
"""
