"""jax.profiler trace annotations for the step functions.

``scope(name)`` wraps a tracing-time ``jax.named_scope``: the name lands in
the HLO op metadata of everything traced inside it, so an on-chip
``jax.profiler`` capture (when the TPU tunnel is up) groups kernels by code
region — event selection, data-sync handlers, node update, queue routing,
commit delivery — instead of one undifferentiated fusion soup.  Pure
metadata: instruction counts, fusion decisions, and numerics are untouched
(the kernel-census CI gate pins this), so the scopes are always on.
"""

from __future__ import annotations

import contextlib

import jax


def scope(name: str):
    """Named tracing scope ``librabft/<name>`` (no-op off-trace)."""
    try:
        return jax.named_scope(f"librabft/{name}")
    except Exception:  # pragma: no cover - ancient jax fallback
        return contextlib.nullcontext()
