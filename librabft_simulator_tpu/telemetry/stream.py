"""Live fleet health stream: the per-chunk digest, the in-graph consensus
watchdog, and the host-side timeline.

PR 2's metrics plane and PR 3's fleet runtime only meet *after* a run: the
pipelined ``run_sharded`` loop polls one halt scalar per chunk and the
plane is decoded once at the end, so a stalled / leaking / unsafe
100k-instance fleet is invisible until completion.  This module makes the
fleet observable *while it runs* without adding a single host sync — the
digest rides the per-chunk halt poll the host already pays for:

* **Digest** — a small fixed ``[D]`` int32 vector summarizing the whole
  fleet (halted count, events, commits, drops, overflow, live queue
  pressure, min/max committed round, watchdog trip counts), computed
  in-graph at the end of every chunk and psum/pmax/pmin-reduced across the
  mesh.  ``run_sharded``'s one blocking fetch per chunk transfers this
  vector *instead of* the bare halt scalar (slot 0 IS the halt count), so
  live visibility costs zero additional syncs and keeps double-buffering
  intact.  The single-chip engines expose the same contract via
  ``make_run_fn(..., digest=True)``.

* **Watchdog** — an in-graph ``[WD]`` int32 plane per instance
  (:data:`WD_SLOTS`) accumulated inside the step with the same
  fusion-friendly elementwise discipline as the telemetry plane (no scalar
  scatters): liveness stall (no pacemaker round advance for a static
  threshold of processed events — the HotStuff/LibraBFT framing of
  liveness as monitorable pacemaker progress), queue-pressure saturation,
  sync-jump anomaly, and the safety invariants (conflicting commit at the
  same height across nodes; round regression inside one node's committed
  chain, epoch-aware via the depth-derived epoch).  Behind static
  ``SimParams.watchdog``, default OFF: the off graph is bit- and
  kernel-identical (the wd leaf is zero-width and every update is skipped
  at trace time), pinned by tests/test_stream.py and the kernel-census CI
  gate.

* **Timeline** — :class:`TimelineRecorder` collects the per-chunk digests
  into a host-side time series (per-chunk ev/s, halt progress, ETA), emits
  NDJSON for ``scripts/fleet_watch.py``'s live view, and summarizes into
  telemetry/report.py run-reports, bench.py (``BENCH_STREAM=1``) and
  analysis/sweeps.py (``--stream-out``).

The digest and plane slot maps are frozen behind :data:`REGISTRY_VERSION`:
decoders (report.py, :func:`load_ndjson`) refuse artifacts written under a
different version, and tests/test_stream.py pins the committed slot order,
so reordering slots can never silently corrupt decoded reports.
"""

from __future__ import annotations

import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

I32 = jnp.int32

# The frozen slot maps now live in telemetry/schema.py (the single
# version table every serialized surface shares); the historical public
# names are re-exported here so traced code and the test pins keep one
# import path.  The digest slot registry maps name -> (index, mesh
# aggregation); D is fixed regardless of SimParams (watchdog slots read 0
# when the watchdog is off), so every consumer — the poll loop, NDJSON
# rows, the oracle mirror — sees one stable schema.
from .schema import (DIGEST_SLOTS, DIGEST_WIDTH, MAX, MIN,  # noqa: F401
                     REGISTRY_VERSION, SUM, WD_DETECTORS)

SLOT = {name: i for i, (name, _) in enumerate(DIGEST_SLOTS)}

# ---------------------------------------------------------------------------
# Watchdog plane: per-instance [WD] int32 (zero-width when
# SimParams.watchdog is off).  Slot 0 is internal detector state; the rest
# are monotone trip counters (summed across the fleet by the digest).
# ---------------------------------------------------------------------------

WD_STALL_EV = 0         # events since the last pacemaker round advance
WD_STALL = 1            # liveness-stall trips (threshold crossings)
WD_QUEUE_SAT = 2        # steps/windows at queue/inbox saturation
WD_SYNC_JUMP = 3        # state-sync jump anomalies observed
WD_SAFETY_CONFLICT = 4  # conflicting commit at the same height
WD_ROUND_REGRESS = 5    # round regression inside a committed chain
WD_SLOTS = ("stall_ev", "stall", "queue_sat", "sync_jump",
            "safety_conflict", "round_regress")
WD_WIDTH = len(WD_SLOTS)


def wd_width(p) -> int:
    """Watchdog plane length (0 when the watchdog is off)."""
    return WD_WIDTH if p.watchdog else 0


def init_wd(p, shape=()):
    """Zero watchdog plane ([WD] per instance; [0] when off)."""
    return jnp.zeros(shape + (wd_width(p),), I32)


# ---------------------------------------------------------------------------
# Device-side digest.
# ---------------------------------------------------------------------------


def compute_digest(p, st, axis_names=None):
    """The fleet-health digest of a (possibly batched) engine state: one
    ``[D]`` int32 vector, fixed slots (:data:`DIGEST_SLOTS`).

    Works on both engine flavors (shared queue vs per-receiver inboxes) in
    their UNPACKED form — the chunk scans unpack at the boundary, so this
    is always traced on ``SimState``/``PSimState``.  All reductions are
    in-graph; with ``axis_names`` the slots additionally psum/pmax/pmin
    across the mesh (shard_map context), so the host sees the whole-fleet
    value from any one shard.  ``queue_depth_max`` is the CURRENT
    occupancy (live pressure at chunk boundary), not the high-water mark —
    the hwm lives in the telemetry plane, which needs ``telemetry`` on;
    the digest works with everything off.  int32 throughout: a fleet
    summing past 2**31 events will wrap — split reporting windows before
    that."""
    comp = {}
    s32 = lambda x: jnp.sum(jnp.asarray(x).astype(I32))  # noqa: E731
    comp["halted"] = s32(st.halted)
    comp["events"] = s32(st.n_events)
    comp["commits"] = s32(st.ctx.commit_count)
    comp["drops"] = s32(st.n_msgs_dropped)
    comp["overflow"] = s32(st.n_queue_full if hasattr(st, "n_queue_full")
                           else st.n_inbox_full)
    if hasattr(st, "queue"):  # serial engine: shared [CM] message table
        occ = jnp.sum(st.queue.valid.astype(I32), axis=-1)
    else:                     # lane engine: [N, IC] per-receiver inboxes
        occ = jnp.sum(st.in_valid.astype(I32), axis=(-2, -1))
    comp["queue_depth_max"] = jnp.max(occ).astype(I32)
    comp["committed_round_min"] = jnp.min(st.store.hcr).astype(I32)
    comp["committed_round_max"] = jnp.max(st.store.hcr).astype(I32)
    if p.watchdog:
        wd_tot = jnp.sum(st.wd.astype(I32).reshape((-1, WD_WIDTH)), axis=0)
        for name in WD_DETECTORS:
            comp["wd_" + name] = wd_tot[WD_SLOTS.index(name)]
    else:
        for name in WD_DETECTORS:
            comp["wd_" + name] = jnp.zeros((), I32)
    if axis_names is not None:
        # Grouped mesh reductions: one collective per aggregation kind.
        groups = {SUM: jax.lax.psum, MAX: jax.lax.pmax, MIN: jax.lax.pmin}
        for agg, red in groups.items():
            names = [n for n, a in DIGEST_SLOTS if a == agg]
            vec = red(jnp.stack([comp[n] for n in names]), axis_names)
            for i, n in enumerate(names):
                comp[n] = vec[i]
    return jnp.stack([comp[n] for n, _ in DIGEST_SLOTS]).astype(I32)


# ---------------------------------------------------------------------------
# Host-side decode / fold.
# ---------------------------------------------------------------------------


def decode_digest(vec) -> dict:
    """A fetched ``[D]`` digest -> named dict, plus the derived
    ``watchdog_flags`` bitmask (bit *i* set iff detector *i* of
    :data:`WD_DETECTORS` has a nonzero trip count)."""
    vec = np.asarray(vec).astype(np.int64)
    if vec.shape != (DIGEST_WIDTH,):
        raise ValueError(
            f"digest shape {vec.shape} != ({DIGEST_WIDTH},); artifact from "
            f"another registry version? (this build is v{REGISTRY_VERSION})")
    out = {name: int(vec[i]) for i, (name, _) in enumerate(DIGEST_SLOTS)}
    out["watchdog_flags"] = sum(
        (1 << i) for i, d in enumerate(WD_DETECTORS) if out["wd_" + d] > 0)
    return out


def pad_digest() -> dict:
    """The digest contribution of ONE pre-halted padding instance (see
    parallel/sharded.pad_to_multiple): halted, everything else zero.  Lets
    tests fold oracle per-instance digests into the padded-fleet value."""
    d = {name: 0 for name, _ in DIGEST_SLOTS}
    d["halted"] = 1
    return d


def fold_digests(rows) -> dict:
    """Fold per-instance digest dicts (e.g. the oracle mirror's) into one
    fleet digest with the device aggregation per slot — the host-side
    associative twin of :func:`compute_digest`'s mesh reduction."""
    rows = list(rows)
    if not rows:
        raise ValueError("fold_digests needs at least one digest row")
    out = {}
    for name, agg in DIGEST_SLOTS:
        vals = [int(r[name]) for r in rows]
        out[name] = (sum(vals) if agg == SUM
                     else max(vals) if agg == MAX else min(vals))
    out["watchdog_flags"] = sum(
        (1 << i) for i, d in enumerate(WD_DETECTORS) if out["wd_" + d] > 0)
    return out


# ---------------------------------------------------------------------------
# Host timeline.
# ---------------------------------------------------------------------------


class TimelineRecorder:
    """Collects per-chunk digests into a time series and (optionally)
    streams NDJSON.

    One :meth:`record` call per polled chunk: the row carries the decoded
    digest plus derived rates (events/s since the previous chunk, halt
    progress, a crude halted-rate ETA).  ``out`` (a path or a file-like
    object) additionally gets one JSON line per row, preceded by a meta
    line carrying :data:`REGISTRY_VERSION` — the live view
    (scripts/fleet_watch.py) and :func:`load_ndjson` verify it before
    decoding anything."""

    def __init__(self, p, total_instances=None, out=None, meta=None):
        self.p = p
        self.total_instances = total_instances
        self.rows = []
        self._owns_out = isinstance(out, str)
        self._out = open(out, "w") if self._owns_out else out
        # Row writes serialize under this lock: the resident fleet
        # service emits request rows from operator threads (submit())
        # while the serve thread streams digests onto the SAME file —
        # interleaved buffered writes would land a corrupt NON-final
        # line, which load_ndjson refuses loudly (by design).
        self._wlock = threading.Lock()
        self._t0 = self._last_t = time.perf_counter()
        self._last_events = 0
        header = {
            "kind": "meta",
            "registry_version": REGISTRY_VERSION,
            "digest_slots": [name for name, _ in DIGEST_SLOTS],
            "n_nodes": p.n_nodes,
            "watchdog": bool(p.watchdog),
            "total_instances": total_instances,
        }
        if meta:
            header.update(meta)
        self._emit(header)

    def _emit(self, obj) -> None:
        if self._out is not None:
            with self._wlock:
                self._out.write(json.dumps(obj) + "\n")
                self._out.flush()

    def emit(self, obj: dict) -> None:
        """Append one extra NDJSON line to the stream (no-op without an
        ``out``).  The resident fleet service (serve/service.py) rides its
        request-lifecycle rows (``kind="request"``) on the digest stream
        this way, so ``fleet_watch --serve`` follows one file.  Rows must
        carry a ``kind`` other than meta/fleet/row — decoders dispatch on
        it."""
        self._emit(obj)

    def set_fleet(self, total: int, n_valid: int) -> None:
        """Fleet geometry from the runner (parallel/sharded.run_sharded):
        ``total`` is the PADDED instance count — what the digest's
        ``halted`` slot counts, pre-halted padding included — and
        ``n_valid`` the real instances.  Rows stay raw (bit-pinnable
        against the device digest); consumers subtract
        ``total - n_valid`` for a real-instance halt view.  Overrides a
        constructor ``total_instances`` only when none was given."""
        if self.total_instances is None:
            self.total_instances = total
        self._emit({"kind": "fleet", "total_instances": total,
                    "n_valid": n_valid, "padding": total - n_valid})

    def record(self, digest, steps=None) -> dict:
        """Append one chunk's digest (an already-fetched ``[D]`` vector);
        returns the derived row."""
        t = time.perf_counter()
        d = decode_digest(digest)
        dt = max(t - self._last_t, 1e-9)
        elapsed = t - self._t0
        row = {
            "kind": "row",
            "chunk": len(self.rows),
            "t_s": round(elapsed, 6),
            "steps": steps,
            **d,
            "ev_per_s": round((d["events"] - self._last_events) / dt, 1),
        }
        if self.total_instances:
            row["halt_frac"] = round(d["halted"] / self.total_instances, 6)
            # Crude ETA from the mean halting rate so far; None until the
            # first instance halts (no rate to extrapolate from).
            row["eta_s"] = (
                round(elapsed * (self.total_instances - d["halted"])
                      / d["halted"], 3)
                if d["halted"] > 0 and elapsed > 0 else None)
        self._last_t = t
        self._last_events = d["events"]
        self.rows.append(row)
        self._emit(row)
        return row

    def record_ring(self, ring, retired, steps=None) -> list[dict]:
        """Append one outer call's retired digest-ring rows (the
        ``wrap="device"`` dispatch of parallel/sharded.py): the first
        ``retired`` rows of an already-fetched ``[ring_k, D]`` ring,
        oldest first.  There was ONE host egress, so all rows land under
        one poll timestamp, annotated ``ring_i``/``ring_n``
        (schema.RING_ROW_FIELDS) so viewers can tell a ring batch from
        per-chunk polls.  Each row still carries its own chunk's TRUE
        cumulative counters — ring rows are in-state digests, so
        consecutive differences are exact per-chunk deltas and the
        observatory's windowed rollups difference them like any other
        rows.  ``ev_per_s`` attributes the poll interval evenly across
        the batch (the host cannot observe sub-poll timing).  ``steps``
        is an optional sequence of per-row step counts (length >=
        ``retired``)."""
        ring = np.asarray(ring)
        n = int(retired)
        if not 1 <= n <= ring.shape[0]:
            raise ValueError(
                f"retired={n} outside the ring's [1, {ring.shape[0]}] rows")
        t = time.perf_counter()
        dt = max(t - self._last_t, 1e-9)
        elapsed = t - self._t0
        per = dt / n
        out = []
        for i in range(n):
            d = decode_digest(ring[i])
            row = {
                "kind": "row",
                "chunk": len(self.rows),
                "t_s": round(elapsed, 6),
                "steps": None if steps is None else steps[i],
                "ring_i": i,
                "ring_n": n,
                **d,
                "ev_per_s": round((d["events"] - self._last_events) / per,
                                  1),
            }
            if self.total_instances:
                row["halt_frac"] = round(
                    d["halted"] / self.total_instances, 6)
                row["eta_s"] = (
                    round(elapsed * (self.total_instances - d["halted"])
                          / d["halted"], 3)
                    if d["halted"] > 0 and elapsed > 0 else None)
            self._last_events = d["events"]
            self.rows.append(row)
            self._emit(row)
            out.append(row)
        self._last_t = t
        return out

    def summary(self, tail: int = 8) -> dict:
        """The compact block run-reports / bench rows attach: registry
        version, chunk count, final digest, mean throughput, and the last
        ``tail`` rows of the timeline."""
        if not self.rows:
            return {"registry_version": REGISTRY_VERSION, "chunks": 0}
        last = self.rows[-1]
        elapsed = max(last["t_s"], 1e-9)
        return {
            "registry_version": REGISTRY_VERSION,
            "chunks": len(self.rows),
            "elapsed_s": last["t_s"],
            "final": {name: last[name] for name, _ in DIGEST_SLOTS},
            "watchdog_flags": last["watchdog_flags"],
            "mean_ev_per_s": round(last["events"] / elapsed, 1),
            "timeline_tail": self.rows[-tail:],
        }

    def close(self) -> None:
        if self._owns_out and self._out is not None:
            self._out.close()
            self._out = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def load_ndjson(path: str) -> tuple[dict, list[dict]]:
    """Read a stream file back: ``(meta, rows)``.  Refuses (clear error) a
    file written under a different :data:`REGISTRY_VERSION` — the slot maps
    are frozen per version, and decoding across versions would silently
    misattribute slots.

    Tolerates a truncated FINAL line (the mid-write tail of a run still
    streaming, or of a timeout-killed writer — ledger.read_ndjson); a
    corrupt line anywhere else still raises.  Canonical implementation in
    the jax-free observatory ingest (telemetry/observatory.load_stream);
    this delegate keeps the historical import path."""
    from . import observatory

    return observatory.load_stream(path)
