"""Fleet observatory: the unified cross-stream event store, windowed
rollups, and the cross-host trace merge.

The repo's observability grew one stream at a time — per-host ``[13]``
digest NDJSON (stream.py), runtime-ledger span/compile rows (ledger.py),
serve request-lifecycle rows riding the digest stream (serve/service.py)
— and every consumer (fleet_watch's four views, report decoders, tests)
parsed its own kind privately.  This module is the one ingest layer over
all of them:

* **Unified event store** — :meth:`Observatory.ingest` sniffs any repo
  NDJSON artifact (fleet digest stream, ``<base>.p<pid>`` per-host
  streams, serve stream, runtime ledger), version-checks it against the
  telemetry/schema.py table with the SAME refusal messages the private
  loaders always raised, and lands every row in one tagged store keyed by
  host / stream / kind / run / chunk / request.  :func:`load_stream` is
  the jax-free fleet-stream loader (stream.load_ndjson delegates here),
  so viewers never pay a backend import.

* **Windowed rollups** — :meth:`Observatory.rollup` folds the digest
  time series into fixed windows (``LIBRABFT_OBS_WINDOW_S``): monotone
  counters (schema.COUNTER_SLOTS) become per-window deltas, gauges fold
  with their registered digest aggregation, and :meth:`histogram` buckets
  raw samples into the same geometric bins as the in-graph telemetry
  plane (utils/quantile.py), with bounded p50/p99 readouts.

* **Cross-host trace merge** — each process's ledger epoch is its own
  ``perf_counter`` zero, incomparable across hosts.  The distributed
  bootstrap records the ``jax.distributed.initialize`` barrier as a
  ``handshake`` span (distributed/bootstrap.py): all processes leave the
  coordinator handshake at (nearly) the same wall instant, so aligning
  the handshake-span ENDS gives per-host clock offsets
  (:meth:`clock_offsets`) without any wall-clock exchange, and
  :meth:`merged_perfetto` exports ONE Chrome-trace/Perfetto JSON with
  every host's spans on its own process track, correctly interleaved
  (``scripts/fleet_watch.py --timeline``).

Strictly host-side and jax-free (ledger + schema + numpy + the quantile
tables): nothing here can touch a trace, so the compiled graphs are
byte-identical with the observatory armed (pinned by
tests/test_observatory.py, the ledger-inertness pattern).
"""

from __future__ import annotations

import glob as _glob
import json
import os
import re

import numpy as np

from ..utils import quantile
from . import ledger as tledger
from . import schema

#: Env knob: rollup window length in seconds (float; default 1.0).
WINDOW_ENV = "LIBRABFT_OBS_WINDOW_S"
DEFAULT_WINDOW_S = 1.0

#: Stream families the sniffer can identify (the tag every stored event
#: carries as ``_stream``).
FLEET = "fleet"     # digest stream (TimelineRecorder; kind row/fleet/...)
SERVE = "serve"     # digest stream with the serve marker + request rows
LEDGER = "ledger"   # runtime-ledger span/compile/run/summary rows

#: Per-host stream suffix (distributed.egress.host_stream_path writes
#: <base>.p<pid>.ndjson; local_cluster ledgers are ledger-p<pid>.ndjson).
_HOST_RE = re.compile(r"[.\-]p(\d+)\.ndjson$")


def _window_from_env() -> float:
    raw = os.environ.get(WINDOW_ENV, "").strip()
    return float(raw) if raw else DEFAULT_WINDOW_S


def load_stream(path: str) -> tuple[dict, list[dict]]:
    """Read a fleet/serve digest-stream file back: ``(meta, rows)``.

    The canonical (jax-free) implementation of stream.load_ndjson, which
    delegates here — refusal contract unchanged: a foreign
    registry_version and a meta-less file both fail loud, a truncated
    FINAL line is tolerated (ledger.read_ndjson)."""
    meta, rows = None, []
    for obj in tledger.read_ndjson(path):
        if obj.get("kind") == "meta":
            schema.require_registry_version(
                obj.get("registry_version"), what=f"stream file {path}")
            meta = obj
        else:
            rows.append(obj)
    if meta is None:
        raise ValueError(
            f"stream file {path} has no meta line; not a fleet-stream "
            "NDJSON artifact (or written by a pre-stream build, or still "
            "empty — retry once the run has started)")
    return meta, rows


def sniff(path: str) -> str:
    """Which stream family a repo NDJSON artifact belongs to (by its meta
    line): :data:`FLEET`, :data:`SERVE`, or :data:`LEDGER`.  Meta-less /
    foreign files fail with the fleet-stream refusal (the common case: a
    still-empty stream)."""
    for obj in tledger.read_ndjson(path):
        if obj.get("kind") != "meta":
            continue
        if obj.get("schema") == "runtime_ledger":
            return LEDGER
        if "registry_version" in obj:
            return SERVE if obj.get("serve") else FLEET
        break
    raise ValueError(
        f"stream file {path} has no meta line; not a fleet-stream "
        "NDJSON artifact (or written by a pre-stream build, or still "
        "empty — retry once the run has started)")


def host_label(path: str, meta: dict) -> str:
    """The host tag for one stream file: the writer's process index when
    the meta carries one (fleet streams from distributed/workers.py),
    else the ``.p<pid>`` / ``-p<pid>`` filename convention, else host 0
    (single-process artifacts)."""
    pid = meta.get("process_id")
    if pid is None:
        m = _HOST_RE.search(path)
        pid = int(m.group(1)) if m else 0
    return f"p{int(pid)}"


class Observatory:
    """The tagged cross-stream event store + query API.

    Every ingested row is stored as-written plus three reserved tags:
    ``_stream`` (fleet/serve/ledger), ``_host`` (``p<k>``), ``_path``
    (source file).  Querying never mutates; one Observatory can hold a
    whole cluster run's artifacts (every per-host stream + every per-host
    ledger) and answer across them."""

    def __init__(self, window_s: float | None = None):
        self.window_s = window_s if window_s is not None \
            else _window_from_env()
        self.sources: list[dict] = []   # {path, stream, host, meta}
        self.events: list[dict] = []

    # -- ingest ----------------------------------------------------------

    def ingest(self, path: str, host: str | None = None) -> dict:
        """Sniff + load one NDJSON artifact into the store; returns the
        source record.  Version refusals are the original loaders' (the
        schema.py table): foreign artifacts never half-ingest."""
        kind = sniff(path)
        if kind == LEDGER:
            meta, rows = tledger.load_ndjson(path)
        else:
            meta, rows = load_stream(path)
        tag = host if host is not None else host_label(path, meta)
        src = {"path": path, "stream": kind, "host": tag, "meta": meta}
        self.sources.append(src)
        for r in rows:
            self.events.append(dict(r, _stream=kind, _host=tag,
                                    _path=path))
        return src

    def ingest_glob(self, pattern: str) -> list[dict]:
        """Ingest every file a glob matches (per-host stream / ledger
        sets); zero matches fails loud — the fleet_watch --merge
        contract."""
        paths = sorted(_glob.glob(pattern))
        if not paths:
            raise ValueError(
                f"{pattern!r} matched no files (per-host streams are "
                "named <base>.p<pid>.ndjson — distributed.egress."
                "host_stream_path; per-host ledgers ledger-p<pid>.ndjson "
                "— distributed.local_cluster)")
        return [self.ingest(p) for p in paths]

    # -- query -----------------------------------------------------------

    def select(self, stream: str | None = None, kind: str | None = None,
               host: str | None = None, run: int | None = None,
               chunk: int | None = None, request: str | None = None,
               since: float | None = None,
               until: float | None = None) -> list[dict]:
        """Filtered events (stored order).  ``since``/``until`` bound the
        row's native timestamp (``t_s`` for stream rows, ``t0_s`` for
        ledger spans); rows with no timestamp only survive an unbounded
        query."""
        out = []
        for e in self.events:
            if stream is not None and e["_stream"] != stream:
                continue
            if kind is not None and e.get("kind") != kind:
                continue
            if host is not None and e["_host"] != host:
                continue
            if run is not None and e.get("run") != run:
                continue
            if chunk is not None and e.get("chunk") != chunk:
                continue
            if request is not None and e.get("id") != request:
                continue
            if since is not None or until is not None:
                t = e.get("t_s", e.get("t0_s"))
                if t is None:
                    continue
                if since is not None and t < since:
                    continue
                if until is not None and t >= until:
                    continue
            out.append(e)
        return out

    def hosts(self) -> list[str]:
        return sorted({s["host"] for s in self.sources})

    def series(self, field: str, kind: str = "row",
               host: str | None = None) -> list[tuple[float, float]]:
        """One field's time series: [(t_s, value)] over matching rows
        that carry both."""
        return [(e["t_s"], e[field])
                for e in self.select(kind=kind, host=host)
                if "t_s" in e and field in e]

    def final_digest(self, host: str | None = None) -> dict | None:
        """The last digest row's decoded slots (+ watchdog_flags).  The
        in-graph digest is mesh-reduced, so ANY host's final row reports
        the whole fleet; per-host reads are the cross-check."""
        rows = self.select(stream=None, kind="row", host=host)
        rows = [r for r in rows if r["_stream"] in (FLEET, SERVE)]
        if not rows:
            return None
        last = max(rows, key=lambda r: (r.get("t_s", 0.0),
                                        r.get("chunk", 0)))
        out = {n: last[n] for n, _ in schema.DIGEST_SLOTS if n in last}
        if "watchdog_flags" in last:
            out["watchdog_flags"] = last["watchdog_flags"]
        return out

    def requests(self) -> dict[str, list[dict]]:
        """Serve request-lifecycle rows grouped by request id, each
        group in stored (chronological) order."""
        out: dict[str, list[dict]] = {}
        for e in self.select(kind="request"):
            out.setdefault(str(e.get("id")), []).append(e)
        return out

    # -- rollups ---------------------------------------------------------

    def rollup(self, window_s: float | None = None,
               host: str | None = None) -> list[dict]:
        """The digest time series folded into fixed windows.

        Monotone cumulative counters (schema.COUNTER_SLOTS) report the
        per-window DELTA (events this window, not since boot); gauges
        fold with their registered digest aggregation (queue pressure
        max, committed-round min/max span); ``halted`` reports its last
        value (fleet halt progress).  Each window row carries
        ``t0_s``/``t1_s``/``rows`` plus an ``ev_per_s`` rate.  One host's
        view when ``host`` is given; otherwise host p0's stream if
        present (every host's digest is mesh-reduced — summing across
        hosts would double-count the fleet).

        Ring-batched rows (``wrap="device"``: K rows under ONE poll
        timestamp, stream.TimelineRecorder.record_ring) fold like any
        other rows: each ring row is its chunk's TRUE cumulative digest,
        so windowing by the LAST row per window yields the exact sum of
        the K per-chunk deltas — never one collapsed poll's worth.  Rows
        sort by (t_s, chunk) so a ring batch keeps retirement order even
        at equal timestamps, and windows report ``ring_rows`` (how many
        of their rows came from ring batches) when any did."""
        w = window_s if window_s is not None else self.window_s
        if host is None:
            hosts = self.hosts()
            host = "p0" if "p0" in hosts else (hosts[0] if hosts else None)
        rows = sorted((r for r in self.select(kind="row", host=host)
                       if "t_s" in r),
                      key=lambda r: (r["t_s"], r.get("chunk", 0)))
        if not rows:
            return []
        counters = [n for n, _ in schema.DIGEST_SLOTS
                    if n in schema.COUNTER_SLOTS]
        gauges = [(n, agg) for n, agg in schema.DIGEST_SLOTS
                  if n not in schema.COUNTER_SLOTS]
        out = []
        prev = {n: 0 for n in counters}  # cumulative value before window
        k = 0
        i = 0
        while i < len(rows):
            t0, t1 = k * w, (k + 1) * w
            k += 1
            wrows = []
            while i < len(rows) and rows[i]["t_s"] < t1:
                wrows.append(rows[i])
                i += 1
            if not wrows:
                continue  # empty windows are omitted, not zero-filled
            last = wrows[-1]
            win = {"t0_s": t0, "t1_s": t1, "rows": len(wrows),
                   "host": host}
            ring_rows = sum(1 for r in wrows if "ring_i" in r)
            if ring_rows:
                win["ring_rows"] = ring_rows
            for n in counters:
                cur = int(last.get(n, prev[n]))
                win[n] = cur - prev[n]
                prev[n] = cur
            for n, agg in gauges:
                vals = [int(r[n]) for r in wrows if n in r]
                if not vals:
                    continue
                if n == "halted":
                    win[n] = vals[-1]
                elif agg == schema.MAX:
                    win[n] = max(vals)
                elif agg == schema.MIN:
                    win[n] = min(vals)
                else:
                    win[n] = vals[-1]
            span = max(last["t_s"] - t0, 1e-9) if not out \
                else max(last["t_s"] - out[-1]["_t_last"], 1e-9)
            win["ev_per_s"] = round(win.get("events", 0) / span, 1)
            win["_t_last"] = last["t_s"]
            out.append(win)
        for winrow in out:
            winrow.pop("_t_last", None)
        return out

    @staticmethod
    def histogram(values) -> dict:
        """Raw samples -> the telemetry plane's geometric buckets
        (utils/quantile.py) with bounded p50/p99 — the host-side twin of
        the in-graph latency histograms, for sample sets that never went
        through the plane (serve admission latencies, sentinel reps)."""
        vals = np.asarray(list(values), dtype=np.float64)
        counts = np.zeros(quantile.HIST_BUCKETS, dtype=np.int64)
        if vals.size:
            b = quantile.bucket_np(np.maximum(vals, 0).astype(np.int64))
            np.add.at(counts, b, 1)
        return {"counts": [int(c) for c in counts],
                "p50_bounds": list(quantile.histogram_quantile(counts, .5)),
                "p99_bounds": list(quantile.histogram_quantile(counts, .99))}

    # -- cross-host trace merge ------------------------------------------

    def clock_offsets(self) -> dict[str, float]:
        """Per-host seconds to ADD to a host's ledger timestamps to land
        them on the reference host's clock (the lowest-numbered host with
        a handshake span; offset 0.0 for it and for hosts that never
        recorded one — single-process ledgers are their own reference).

        Anchor: the ``handshake`` span around jax.distributed.initialize
        (distributed/bootstrap.py) ENDS when the coordinator releases all
        processes — the same wall instant everywhere up to barrier skew,
        which is orders below the chunk timescale this merge serves."""
        ends: dict[str, float] = {}
        for e in self.select(stream=LEDGER, kind="span"):
            if e.get("name") != tledger.HANDSHAKE:
                continue
            end = float(e["t0_s"]) + float(e["dur_s"])
            # Keep the FIRST handshake per host (re-inits re-anchor
            # nothing: initialize is once-only per process).
            ends.setdefault(e["_host"], end)
        offsets = {h: 0.0 for h in self.hosts()}
        if not ends:
            return offsets
        ref = sorted(ends)[0]
        for h, end in ends.items():
            offsets[h] = ends[ref] - end
        return offsets

    def merged_perfetto(self, path: str | None = None) -> dict:
        """ONE Chrome-trace/Perfetto JSON over every ingested ledger:
        each host is a process track (pid = host index, labeled via 'M'
        process_name metadata), span timestamps shifted by
        :meth:`clock_offsets` so cross-host ordering is real.  Load in
        ui.perfetto.dev; host dispatch/poll spans from all processes
        interleave on one timeline (tunnel-checklist item 10's host
        half)."""
        offsets = self.clock_offsets()
        events = []
        seen_hosts = []
        for e in self.select(stream=LEDGER, kind="span"):
            h = e["_host"]
            pid = int(h[1:]) if h[1:].isdigit() else 0
            if h not in seen_hosts:
                seen_hosts.append(h)
                events.append({"name": "process_name", "ph": "M",
                               "pid": pid,
                               "args": {"name": f"host {h}"}})
            attrs = {k: v for k, v in e.items()
                     if k not in ("kind", "name", "t0_s", "dur_s",
                                  "thread", "parent", "depth", "_stream",
                                  "_host", "_path")}
            events.append({
                "name": e["name"],
                "cat": "librabft_host",
                "ph": "X",
                "ts": round((float(e["t0_s"]) + offsets.get(h, 0.0)) * 1e6,
                            3),
                "dur": round(float(e["dur_s"]) * 1e6, 3),
                "pid": pid,
                "tid": e.get("thread", 0),
                "args": dict(attrs, host=h),
            })
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"schema": "runtime_ledger",
                          "ledger_version": schema.LEDGER_VERSION,
                          "hosts": sorted(offsets),
                          "clock_offsets_s": {h: round(o, 6)
                                              for h, o in offsets.items()}},
        }
        if path is not None:
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc


def from_paths(paths, window_s: float | None = None) -> Observatory:
    """Build a store over a list of artifact paths (the one-shot viewer
    entry: fleet_watch hands every matched file here)."""
    obs = Observatory(window_s=window_s)
    for p in paths:
        obs.ingest(p)
    return obs
