"""The one NDJSON/artifact schema-version table — every serialized
observability surface, single-sourced.

Three stream families accreted their own private version stamps across
PRs 4/7/9: the fleet digest stream froze its slot maps behind
``stream.REGISTRY_VERSION``, the runtime ledger stamped
``ledger_version`` on its meta line, and the resident service's
save/restore sidecar carried ``serve_version`` — three constants, three
refusal paths, three places a version bump could be forgotten.  This
module is the hoist: one table of every stream kind's frozen version,
consumed by the writers (stream.py, ledger.py, serve/service.py — their
public constants are re-exports of this table), by each loader's refusal
path, and by the observatory ingest (:mod:`.observatory`), which
dispatches on the meta line's kind and refuses a foreign version with
the SAME messages the private loaders always used.

Strictly jax-free and numpy-free: the viewers (scripts/fleet_watch.py,
scripts/bench_index.py) and the ledger CLI import this from processes
that never touch a backend.
"""

from __future__ import annotations

#: Frozen schema version per serialized stream kind.  Bump an entry when
#: ANY field/slot of that kind is added, removed, or reordered; every
#: decoder hard-refuses a mismatch.  Exception: strictly ADDITIVE
#: per-row annotations that no decoder dispatches on (RING_ROW_FIELDS
#: below) land without a bump — a v-N reader decodes the row correctly
#: by ignoring them, which is the opposite of the silent-misattribution
#: hazard the version gate exists for.
VERSIONS = {
    # The fleet digest stream (telemetry/stream.py): the telemetry-plane
    # registration order + the digest/watchdog slot orders below.
    "fleet_stream": 1,
    # The runtime-ledger span/compile stream (telemetry/ledger.py) and
    # its Perfetto export.
    "runtime_ledger": 1,
    # The resident service's save/restore sidecar (serve/service.py).
    "serve_state": 1,
    # The perf-regression sentinel's committed bench history rows
    # (scripts/perf_sentinel.py -> BENCH_HISTORY.ndjson).
    "bench_history": 1,
}

#: The writers' historical constant names, re-exported for call sites.
REGISTRY_VERSION = VERSIONS["fleet_stream"]
LEDGER_VERSION = VERSIONS["runtime_ledger"]
SERVE_VERSION = VERSIONS["serve_state"]
BENCH_HISTORY_VERSION = VERSIONS["bench_history"]

# ---------------------------------------------------------------------------
# Digest slot registry (hoisted from stream.py, which re-exports): the
# jax-free consumers (observatory rollups, fleet_watch, bench_index) need
# the slot names AND their fold kinds without importing the traced side.
# ---------------------------------------------------------------------------

SUM, MAX, MIN = "sum", "max", "min"

DIGEST_SLOTS = (
    ("halted", SUM),                # instances halted (slot 0 IS the poll)
    ("events", SUM),                # total events processed
    ("commits", SUM),               # total per-node commit_count
    ("drops", SUM),                 # network drops
    ("overflow", SUM),              # queue/inbox overflow
    ("queue_depth_max", MAX),       # live (current) per-instance occupancy
    ("committed_round_min", MIN),   # min over all nodes' hcr
    ("committed_round_max", MAX),   # max over all nodes' hcr
    ("wd_stall", SUM),              # watchdog trip counts (0 when off)
    ("wd_queue_sat", SUM),
    ("wd_sync_jump", SUM),
    ("wd_safety_conflict", SUM),
    ("wd_round_regress", SUM),
)
DIGEST_WIDTH = len(DIGEST_SLOTS)

#: Watchdog detectors surfaced in the digest, in wd-plane counter order.
WD_DETECTORS = ("stall", "queue_sat", "sync_jump", "safety_conflict",
                "round_regress")

#: Digest slots that are MONOTONE CUMULATIVE totals (windowed rollups
#: difference them); the rest are point-in-time gauges (rollups fold them
#: with their DIGEST_SLOTS aggregation kind instead).
COUNTER_SLOTS = frozenset(
    name for name, agg in DIGEST_SLOTS if agg == SUM) - {"halted"}

#: Ring-batch annotations on ``kind="row"`` lines (wrap="device"
#: dispatch, TimelineRecorder.record_ring): ``ring_i`` is the row's
#: 0-based position within one outer call's retired batch, ``ring_n``
#: the batch size — up to ring_n rows share one host poll timestamp
#: while each keeps its own chunk's true cumulative counters.  Absent on
#: per-chunk-polled (wrap="host") rows; additive-only, so no
#: fleet_stream version bump (see VERSIONS).
RING_ROW_FIELDS = ("ring_i", "ring_n")


def require_registry_version(version, what: str = "artifact") -> None:
    """Refuse to decode an artifact written under a different slot-map
    registry version (the canonical implementation;
    telemetry/report.require_registry_version delegates here).

    The plane/digest/watchdog slot maps are frozen per version — decoding
    a v-N artifact with v-M code would silently misattribute slots (a
    reordered counter reads as a different counter, not as an error), so
    every serialized consumer carries the version and hard-fails on
    mismatch.  ``None`` (a pre-versioning artifact) is a mismatch too."""
    if version != REGISTRY_VERSION:
        raise ValueError(
            f"{what}: slot-registry version {version!r} does not match this "
            f"build's v{REGISTRY_VERSION}; the telemetry plane / "
            "digest / watchdog slot maps are frozen per version and decoding "
            "across versions silently corrupts reports — regenerate the "
            "artifact with this build (or decode with the build that wrote "
            "it)")


def require_ledger_version(version, what: str = "ledger file") -> None:
    """The runtime-ledger twin of :func:`require_registry_version` —
    the exact refusal ledger.load_ndjson has always raised."""
    if version != LEDGER_VERSION:
        raise ValueError(
            f"{what}: ledger_version {version!r} does "
            f"not match this build's v{LEDGER_VERSION}")


def require_serve_version(version, what: str = "serve sidecar") -> None:
    """The resident-service sidecar twin (serve/service.restore's
    refusal, hoisted verbatim)."""
    if version != SERVE_VERSION:
        raise ValueError(
            f"{what}: serve_version "
            f"{version} != {SERVE_VERSION} (foreign artifact)")
