"""Host-side telemetry exporter: one run-report from metric planes, the
flight recorder, and the DataWriter summary.

Device state stays on device during the run (zero host sync in the hot
loop); this module decodes everything AFTER the run:

* :func:`metrics_dict` — one instance's ``[M]`` plane to named values;
* :func:`merged_metrics` — a batched ``[B, M]`` plane folded across the
  fleet per slot kind (counters/histograms sum, high-water marks max);
* :func:`decode_flight` — the last-K-events ring in chronological order;
* :func:`telemetry_block` — the compact block ``bench.py`` and
  ``analysis/sweeps.py`` attach to their emitted contract lines (event-kind
  counts, loss tallies, queue pressure, p50/p99 latency bounds);
* :func:`run_report` — the full merged report (+ optional DataWriter files);
* :func:`probe_occupancy` — the engine throughput/occupancy probe (this IS
  the probe API; the old ``scripts/occupancy_probe.py`` wrapper is gone).

Histogram quantiles are reported as ``(lo, hi)`` *bucket bounds*: the
geometric buckets (utils/quantile.py) bound the true quantile rather than
estimate it, which keeps the report honest about its own resolution.
"""

from __future__ import annotations

import json
from typing import Optional

import jax
import numpy as np

from ..utils import quantile
from . import plane


def require_registry_version(version, what: str = "artifact") -> None:
    """Refuse to decode an artifact written under a different slot-map
    registry version.  Canonical implementation (and the version table
    itself) in telemetry/schema.py; this delegate keeps the historical
    import path every decoder uses."""
    from . import schema

    schema.require_registry_version(version, what)


def _metrics_np(st, instance: Optional[int] = None) -> np.ndarray:
    m = st.metrics
    if instance is not None:
        m = m[instance]  # slice BEFORE fetching: one row, not the fleet
    return np.asarray(jax.device_get(m))


def _require_one_instance(arr: np.ndarray, batched_ndim: int, what: str):
    if arr.ndim > batched_ndim:
        raise ValueError(
            f"{what}: batched fleet state needs instance=<i> to pick one "
            "instance (use merged_metrics/telemetry_block for fleet "
            "aggregates)")


def metrics_dict(p, st, instance: Optional[int] = None) -> dict:
    """One instance's metrics plane as {slot name: int | list}."""
    m = _metrics_np(st, instance)
    _require_one_instance(m, 1, "metrics_dict")
    return plane.decode(p, m)


def _batch_span(index) -> tuple:
    """(start, stop) of a shard's slice of the leading (instance) dim."""
    s = index[0] if index else slice(None)
    return (s.start or 0, s.stop)


def _plane_partial(p, metrics) -> np.ndarray:
    """Fold a (possibly dp-sharded) metrics plane to one [M] int64 partial.

    Sharded fleet states fold SHARD BY SHARD (each device's local [b, M]
    block is fetched and reduced independently via plane.fold_planes, then
    the partials merge) — the full [B, M] plane never lands in one host
    buffer, which is what lets a 100k-instance fleet report without a
    fleet-sized staging copy.  Unsharded / host states take the same fold
    over their single block."""
    shards = getattr(metrics, "addressable_shards", None)
    if shards is not None and len(shards) > 1:
        partial = None
        seen = set()
        for sh in shards:
            span = _batch_span(sh.index)
            if span in seen:  # replicated copy of an already-folded block
                continue
            seen.add(span)
            partial = plane.fold_planes(p, np.asarray(sh.data), into=partial)
        if partial is not None:
            return partial
    return plane.fold_planes(p, np.asarray(jax.device_get(metrics)))


def merged_metrics(p, st) -> dict:
    """Fold a (possibly batched, possibly dp-sharded) plane across all
    leading dims: counters and histogram buckets sum over the fleet,
    high-water marks max.  Sharded fleets merge per shard (see
    :func:`_plane_partial`); pre-halted padding instances hold all-zero
    planes and so contribute nothing to either aggregation."""
    vec = _plane_partial(p, st.metrics)
    out = {}
    for name, (off, size, _) in plane.np_registry(p).items():
        vals = vec[off:off + size]
        out[name] = int(vals[0]) if size == 1 else [int(v) for v in vals]
    return out


def _flight_rows(p, fdat: np.ndarray, mdat: np.ndarray, base: int,
                 limit: Optional[int] = None) -> dict:
    """Decode one shard's [b, K, FR_COLS] flight block -> {global instance
    index: chronological row dicts} using the fr_count slots of the
    matching metrics block.  ``limit`` stops decoding at that global
    instance index (instances past it are never touched)."""
    fr_off, _ = plane.slot(p, "fr_count")
    out = {}
    stop = fdat.shape[0] if limit is None else max(min(limit - base,
                                                       fdat.shape[0]), 0)
    for i in range(stop):
        order = plane.ring_order(int(mdat[i, fr_off]), p.flight_cap)
        out[base + i] = [
            dict({name: int(fdat[i, j, col])
                  for col, name in enumerate(plane.FR_NAMES)},
                 instance=base + i)
            for j in order]
    return out


def fleet_flight(p, st, max_instances: Optional[int] = None) -> list[dict]:
    """Every instance's flight-recorder tail, concatenated in global
    instance order with an ``instance`` tag per row — the fleet view of
    :func:`decode_flight`.

    dp-sharded fleets decode shard by shard (flight and metrics blocks are
    fetched per device and matched on their batch span), mirroring the
    metrics merge: no full-fleet ring buffer on one host.  Pre-halted
    padding instances have ``fr_count == 0`` rings and contribute no rows.
    ``max_instances`` truncates to the first k instances (e.g. the valid
    count of a padded fleet)."""
    if not p.telemetry:
        return []
    if np.ndim(st.clock) == 0:  # no data movement: shape-only check
        return [dict(r, instance=0) for r in decode_flight(p, st)]
    rows = {}
    fl_shards = getattr(st.flight, "addressable_shards", None)
    if fl_shards is not None and len(fl_shards) > 1:
        for sh in fl_shards:
            span = _batch_span(sh.index)
            if span[0] in rows or sh.data.shape[0] == 0:
                continue
            if max_instances is not None and span[0] >= max_instances:
                continue  # shard is all truncated instances: skip entirely
            met = next(m for m in st.metrics.addressable_shards
                       if _batch_span(m.index) == span)
            rows.update(_flight_rows(p, np.asarray(sh.data),
                                     np.asarray(met.data), span[0],
                                     limit=max_instances))
    else:
        rows = _flight_rows(p, np.asarray(jax.device_get(st.flight)),
                            np.asarray(jax.device_get(st.metrics)), 0,
                            limit=max_instances)
    return [r for i in sorted(rows) for r in rows[i]]


def decode_flight(p, st, instance: Optional[int] = None) -> list[dict]:
    """The flight-recorder tail, oldest first.

    Serial-engine rows are strictly chronological; parallel-engine rows are
    appended in (window, drain-iteration, lane) order — sort by ``time`` for
    a per-node chronological view."""
    if not p.telemetry:
        return []
    fl = st.flight
    if instance is not None:
        fl = fl[instance]  # slice BEFORE fetching: one ring, not the fleet
    fl = np.asarray(jax.device_get(fl))
    _require_one_instance(fl, 2, "decode_flight")
    count = metrics_dict(p, st, instance)["fr_count"]
    order = plane.ring_order(count, fl.shape[0])
    return [
        {name: int(fl[i, col]) for col, name in enumerate(plane.FR_NAMES)}
        for i in order
    ]


#: (lo, hi) bucket bounds containing the q-th histogram sample — the math
#: now lives jax-free in utils/quantile.py (the observatory rollups share
#: it); this name stays for the report-side callers.
histogram_quantile = quantile.histogram_quantile


def _quantile_block(counts) -> dict:
    p50 = histogram_quantile(counts, 0.50)
    p99 = histogram_quantile(counts, 0.99)
    return {"count": int(np.sum(counts)),
            "p50_bounds": list(p50), "p99_bounds": list(p99)}


def telemetry_block(p, st) -> dict:
    """The compact fleet-level block for contract lines (bench.py JSON,
    sweeps rows): event-kind counts, loss tallies, queue pressure, and
    latency quantile bounds, merged across the whole batch."""
    m = merged_metrics(p, st)
    block = {
        "events": {
            "notify": m["ev_notify"], "request": m["ev_request"],
            "response": m["ev_response"], "timer": m["ev_timer"],
        },
        "drops": m["drops"],
        "overflow": m["overflow"],
        "sync_jumps": m["sync_jumps"],
        "queue_hwm": m["queue_hwm"],
        "node_depth_hwm_max": max(m["node_depth_hwm"]) if m["node_depth_hwm"]
        else 0,
        "round_latency": _quantile_block(m["round_lat_hist"]),
        "commit_latency": _quantile_block(m["commit_lat_hist"]),
        "commit_lat_miss": m["commit_lat_miss"],
        "fr_count": m["fr_count"],
    }
    if m["windows"]:  # lane-engine window health (parallel engine only)
        block["windows"] = m["windows"]
        block["horizon_stall"] = m["horizon_stall"]
        block["lane_spill"] = m["lane_spill"]
    return block


def run_report(p, st, instance: Optional[int] = None,
               data_dir: Optional[str] = None, stream=None) -> dict:
    """The unified run-report: DataWriter summary + merged metrics + the
    decoded flight tail.  ``data_dir`` additionally writes the classic
    DataWriter files (round_switches.txt etc.) there.

    The DataWriter summary and the flight tail are per-instance artifacts
    (DataWriter has always required ``instance`` for batched states), so a
    batched fleet without ``instance`` reports fleet aggregates only
    (merged metrics + telemetry block).

    Every report carries ``registry_version`` (the frozen slot-map version
    — see :func:`require_registry_version`) plus the final fleet-health
    ``digest`` (telemetry/stream.py; works with telemetry off — the digest
    reads engine counters, not the plane).  ``stream`` (the
    TimelineRecorder that observed the run) attaches its per-chunk
    timeline summary as ``stream``."""
    from ..analysis import data_writer as dw
    from . import stream as tstream

    batched = np.asarray(jax.device_get(st.clock)).ndim > 0
    report = {"registry_version": tstream.REGISTRY_VERSION}
    report["digest"] = tstream.decode_digest(
        jax.device_get(tstream.compute_digest(p, st)))
    if instance is not None or not batched:
        if data_dir is not None:
            report["summary"] = dw.DataWriter(p, data_dir).write(st, instance)
        else:
            report["summary"] = dw.summary_dict(p, st, instance)
    if p.telemetry:
        report["telemetry"] = telemetry_block(p, st)
        if batched and instance is None:
            report["metrics"] = merged_metrics(p, st)
        else:
            report["metrics"] = metrics_dict(p, st, instance)
            report["flight"] = decode_flight(p, st, instance)
        report["histogram_edges"] = [
            int(e) for e in quantile.histogram_edges()]
    if stream is not None:
        report["stream"] = stream.summary()
    return report


def save_report(path: str, report: dict) -> None:
    """Serialize a :func:`run_report` dict to JSON (the version rides in
    the report itself)."""
    with open(path, "w") as f:
        json.dump(report, f, indent=1)


def load_report(path: str) -> dict:
    """Read a saved run-report back, refusing (clear error) one written
    under a different slot-registry version — see
    :func:`require_registry_version`."""
    with open(path) as f:
        report = json.load(f)
    require_registry_version(report.get("registry_version"),
                             what=f"run-report {path}")
    return report


def probe_occupancy(engine, p, B: int = 512, chunk: int = 32,
                    reps: int = 3) -> dict:
    """Engine throughput/occupancy probe: run ``reps`` timed chunks of
    ``chunk`` steps over a ``B``-instance fleet and report rates, overflow
    fraction, and — when telemetry is on — the full telemetry block."""
    from ..sim.simulator import dedupe_buffers
    from . import ledger as tledger

    seeds = np.arange(B, dtype=np.uint32)
    st = dedupe_buffers(engine.init_batch(p, seeds))
    run = engine.make_run_fn(p, chunk)
    lg = tledger.get()
    with lg.span(tledger.DISPATCH, what="probe_warmup") as sp_c:
        st = run(st)
        jax.block_until_ready(st)
    compile_s = sp_c.dur_s
    g = lambda x: np.asarray(jax.device_get(x))  # noqa: E731
    e0 = int(g(st.n_events).sum())
    r0 = int((g(st.store.current_round).max(axis=-1) - 1).sum())
    with lg.span(tledger.RUN, what="probe_timed", reps=reps) as sp_t:
        for _ in range(reps):
            st = run(st)
        jax.block_until_ready(st)
    dt = sp_t.dur_s
    e1 = int(g(st.n_events).sum())
    r1 = int((g(st.store.current_round).max(axis=-1) - 1).sum())
    lost_f = st.n_queue_full if hasattr(st, "n_queue_full") else st.n_inbox_full
    lost = int(g(lost_f).sum())
    sent = int(g(st.n_msgs_sent).sum())
    out = {
        "events_per_sec": (e1 - e0) / dt,
        "rounds_per_sec": (r1 - r0) / dt,
        "occupancy": (e1 - e0) / max(chunk * reps * B, 1),
        "compile_s": compile_s,
        "elapsed_s": dt,
        "overflow_frac": lost / max(lost + sent, 1),
        "commits": int(g(st.ctx.commit_count).sum()),
    }
    if p.telemetry:
        out["telemetry"] = telemetry_block(p, st)
    return out
