"""Serving API: submit / poll / drain as a library, NDJSON at the edges.

:class:`FleetService` is the operator-facing wrapper over
:class:`~librabft_simulator_tpu.serve.service.ResidentFleet`: env-knob
defaults (``LIBRABFT_SERVE_SLOTS`` / ``LIBRABFT_SERVE_CHUNK`` /
``LIBRABFT_SERVE_OUT``), NDJSON request-file ingestion
(:func:`load_requests` — the ``scripts/fleet_serve.py`` front-end), result
emission, and checkpoint-based preemption.

Request schema (one JSON object per line)::

    {"id": "req-1", "delay_kind": "pareto", "delay_pareto_scale": 2.0,
     "drop_prob": 0.05, "commit_chain": 2, "byz_kind": "silent",
     "byz_f": 1, "seed": 7, "max_clock": 1200,
     "attack": {"windows": [{"behavior": "equivocate", "start": 100,
                             "end": 400, "targets": [0]}],
                "partition": {"groups": [[0, 1], [2, 3]], "heal": 300}}}

Every field except ``id`` is a :class:`serve.scenario.ScenarioSpec` field
(all optional — defaults are the base params' scenario); unknown fields
fail loud.  ``attack`` takes the adversary/dsl.py program grammar and
needs an adversary-armed base (``SimParams.adversary=True``); the
egressed result then carries the decoded program and — with the
watchdog armed — the per-request safety/liveness trip counts.  Results
stream back as ``kind="request" event="egressed"`` rows on the service
NDJSON (and from :meth:`FleetService.drain`).
"""

from __future__ import annotations

import json
import os

from ..core.types import SimParams
from . import scenario as sc
from .service import ResidentFleet

#: Env knobs (registered in audit/knobs.py; README table generated).
SLOTS_ENV = "LIBRABFT_SERVE_SLOTS"
CHUNK_ENV = "LIBRABFT_SERVE_CHUNK"
OUT_ENV = "LIBRABFT_SERVE_OUT"
#: Serve ring depth: arms the device dispatch wrap (SimParams.wrap=
#: "device") on the resident fleet at this ring_k — admission/egress
#: then land only at outer-call boundaries (up to ring_k chunks apart),
#: trading admission latency for up-to-ring_k-fewer host polls per
#: retired chunk.  Unset = the base params' own wrap/ring_k resolution.
RING_ENV = "LIBRABFT_SERVE_RING_K"


def _int_env(name: str, default: int) -> int:
    env = os.environ.get(name, "").strip()
    if not env:
        return default
    try:
        v = int(env)
    except ValueError:
        raise ValueError(f"{name}={env!r}: want a positive integer")
    if v < 1:
        raise ValueError(f"{name}={env!r}: want a positive integer")
    return v


def load_requests(path: str):
    """Read an NDJSON request file -> ``[(id, ScenarioSpec), ...]``.

    ``id`` defaults to the 1-based line number; malformed lines and
    unknown scenario fields raise with the offending line number (a typo
    must not silently run the default scenario)."""
    out = []
    seen: dict[str, int] = {}
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i}: not JSON ({e})") from None
            if not isinstance(obj, dict):
                raise ValueError(f"{path}:{i}: want a JSON object per line")
            rid = str(obj.pop("id", i))
            if rid in seen:
                raise ValueError(
                    f"{path}:{i}: duplicate request id {rid!r} (first at "
                    f"line {seen[rid]}); ids key the result stream")
            seen[rid] = i
            try:
                spec = sc.ScenarioSpec.from_dict(obj)
            except (TypeError, ValueError) as e:
                raise ValueError(f"{path}:{i}: {e}") from None
            out.append((rid, spec))
    if not out:
        raise ValueError(f"{path}: no requests (empty or comments only)")
    return out


class FleetService:
    """submit/poll/drain over a resident fleet, with env-default config.

    ``base_params`` fixes the structural shape every scenario shares
    (n_nodes, capacities, engine lowering knobs); per-request knobs ride
    the scenario plane.  One instance = one resident executable."""

    def __init__(self, base_params: SimParams | None = None,
                 slots: int | None = None, chunk: int | None = None,
                 mesh=None, engine=None, out: str | None = None,
                 ring_k: int | None = None):
        self.p = base_params if base_params is not None else SimParams(
            n_nodes=4)
        if ring_k is None and os.environ.get(RING_ENV, "").strip():
            ring_k = _int_env(RING_ENV, 0)
        self.fleet = ResidentFleet(
            self.p,
            slots=slots if slots is not None else _int_env(SLOTS_ENV, 8),
            chunk=chunk if chunk is not None else _int_env(CHUNK_ENV, 64),
            mesh=mesh, engine=engine,
            out=out if out is not None else (os.environ.get(OUT_ENV)
                                             or None),
            ring_k=ring_k)

    def submit(self, spec, request_id: str | None = None) -> str:
        return self.fleet.submit(spec, request_id=request_id)

    def submit_file(self, path: str) -> list[str]:
        """Queue every request of an NDJSON file; returns the ids."""
        return [self.fleet.submit(spec, request_id=rid)
                for rid, spec in load_requests(path)]

    def poll(self, request_id: str) -> dict:
        return self.fleet.poll(request_id)

    def serve(self, max_chunks: int | None = None):
        kw = {} if max_chunks is None else {"max_chunks": max_chunks}
        self.fleet.serve(**kw)
        return self

    def drain(self, max_chunks: int | None = None) -> dict:
        kw = {} if max_chunks is None else {"max_chunks": max_chunks}
        return self.fleet.drain(**kw)

    def preempt(self, path: str) -> None:
        """Checkpoint-based eviction: persist the resident state + serve
        bookkeeping and release the device memory claim to the caller."""
        self.fleet.save(path)

    @classmethod
    def resume(cls, path: str, base_params: SimParams, mesh=None,
               engine=None, out: str | None = None) -> "FleetService":
        svc = cls.__new__(cls)
        svc.p = base_params
        svc.fleet = ResidentFleet.restore(
            path, base_params, mesh=mesh, engine=engine,
            out=out if out is not None else (os.environ.get(OUT_ENV)
                                             or None))
        return svc

    def close(self) -> None:
        self.fleet.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
