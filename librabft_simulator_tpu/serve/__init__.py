"""Resident fleet service: continuous batching for simulation-as-a-service.

Three layers (see README "Resident fleet service"):

* :mod:`.scenario` — the per-slot traced scenario plane: the knobs that
  used to be compile-time ``SimParams`` fields (delay distribution, drop
  rate, Byzantine schedule, rng seed, commit rule, horizon) as fixed-shape
  per-instance tensors, so ONE compiled executable serves a heterogeneous
  fleet of scenarios.
* :mod:`.service` — :class:`~librabft_simulator_tpu.serve.service.ResidentFleet`:
  the never-exiting double-buffered chunk loop with an admission queue
  (new scenarios install into *halted* slots via one batched donated
  device write — no recompile) and per-request result egress.
* :mod:`.api` — :class:`~librabft_simulator_tpu.serve.api.FleetService`:
  submit/poll/drain as a library API, NDJSON request/result front-end
  (scripts/fleet_serve.py), graceful drain, and checkpoint-based
  preemption/eviction.
"""

from .scenario import ScenarioPlane, ScenarioSpec  # noqa: F401
from .service import ResidentFleet, ScenarioRequest  # noqa: F401
from .api import FleetService, load_requests  # noqa: F401
