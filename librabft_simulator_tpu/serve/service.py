"""ResidentFleet: the never-exiting fleet loop with an admission queue.

A batch-mode fleet run is compile → run to global halt → land results; the
production regime ("millions of users" submitting scenarios) is the
inference-serving one — continuous batching:

* ONE resident compiled chunk executable stays hot
  (``parallel/sharded.make_sharded_run_fn`` on scenario-armed params: the
  structural key covers every scenario the plane can express, so a serve
  session admitting arbitrarily many distinct configs shows exactly one
  fleet-chunk compile — or aot-hit — on the compile ledger);
* the host loop is ``run_sharded``'s double-buffered discipline (chunk
  k+1 dispatches before chunk k's ``[13]`` digest is polled — still the
  ONE blocking fetch per chunk, via ``sharded._poll_digest``) but never
  exits: between chunks it inspects the polled digest's ``halted`` count,
  egresses finished slots' results (request-tagged, landed host-side with
  one gather per leaf over the finished rows), pops pending
  :class:`ScenarioRequest`s, and installs their scenario rows + fresh init
  state into the freed slots via :func:`serve.scenario.install_rows` —
  one batched donated device write, no recompile;
* request lifecycle (submit → admit → first chunk → egress) is recorded
  as runtime-ledger ``admit``/``egress`` spans and as ``kind="request"``
  rows on the digest NDJSON stream, so ``fleet_watch --serve`` follows
  one file for queue depth, slot occupancy, and per-request ttfc.

Halted slots are observably inert (every engine write is live-gated — the
pre-halted-padding idiom), so installing over them between chunks leaves
every live slot's trajectory bit-identical to an undisturbed run
(tests/test_serve.py pins this leaf-for-leaf).
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import threading
import time
from collections import deque

import jax
import numpy as np

from ..core.types import SimParams
from ..distributed import egress as degress
from ..parallel import mesh as mesh_ops
from ..parallel import sharded
from ..sim import byzantine
from ..sim import simulator as sim_ops
from ..telemetry import ledger as tledger
from ..telemetry import schema as tschema
from ..telemetry import stream as tstream
from ..utils import xops
from . import scenario as sc

#: Per-serve-call chunk ceiling: a runaway scenario (horizon never reached
#: because the caller admitted an effectively-unbounded max_clock) must not
#: wedge the host loop forever.
MAX_CHUNKS_DEFAULT = 10_000


@dataclasses.dataclass
class ScenarioRequest:
    """One queued scenario: id + spec + host-side lifecycle timestamps."""

    request_id: str
    spec: sc.ScenarioSpec
    submitted_t: float = 0.0
    admitted_t: float | None = None
    first_chunk_t: float | None = None
    egressed_t: float | None = None
    slot: int | None = None
    #: Global index of the first dispatched chunk whose INPUT holds this
    #: request's installed rows (admission at a boundary lands in the
    #: in-flight chunk's output, so the request's first executed chunk is
    #: the NEXT dispatch) — first_chunk/ttfc stamp only once that chunk
    #: is polled, and _boundary uses it to ignore the digest lag.
    admit_dispatch: int | None = None

    @property
    def status(self) -> str:
        if self.egressed_t is not None:
            return "egressed"
        if self.admitted_t is not None:
            return "admitted"
        return "pending"

    def ttfc_s(self) -> float | None:
        """Admission-to-first-polled-chunk latency (the serving ttfc)."""
        if self.admitted_t is None or self.first_chunk_t is None:
            return None
        return round(self.first_chunk_t - self.admitted_t, 6)


class ResidentFleet:
    """A resident, continuously-batched scenario-serving fleet.

    ``slots`` fleet slots (rounded up to the mesh size) start halted and
    free; :meth:`submit` queues scenarios; :meth:`serve` pumps the chunk
    loop until the queue and fleet drain (or ``max_chunks``); results
    land in :attr:`results` keyed by request id.  ``out`` streams the
    digest timeline + request rows as NDJSON for ``fleet_watch --serve``.
    """

    def __init__(self, p: SimParams, slots: int = 8, mesh=None,
                 chunk: int = 64, engine=None, out=None, meta=None,
                 fresh_state: bool = True, ring_k: int | None = None):
        self.engine = engine if engine is not None else sim_ops
        self.p = dataclasses.replace(p, scenario=True)
        # Ring-depth serve knob: an explicit ``ring_k`` arms the device
        # dispatch wrap (SimParams.wrap="device") at that depth; without
        # it the base params' own wrap/ring_k resolution (incl. the
        # LIBRABFT_WRAP / LIBRABFT_RING_K envs) decides.  Under the
        # device wrap, admission/egress land only at OUTER-CALL
        # boundaries — up to ring_k chunks between boundaries — so a
        # deeper ring buys fewer host polls at the cost of admission
        # latency (the BENCH_RING serve rungs quantify the tradeoff).
        if ring_k is not None:
            self.p = dataclasses.replace(self.p, wrap="device",
                                         ring_k=int(ring_k))
        rp = xops.resolve_params(self.p)
        self._ring_k = rp.ring_k if rp.wrap == "device" else None
        self.mesh = mesh if mesh is not None else mesh_ops.make_mesh(n_dp=1)
        self.slots = -(-slots // self.mesh.size) * self.mesh.size
        self.chunk = int(chunk)
        # Multi-process meshes (distributed/bootstrap.py): the chunk loop
        # and admission write are SPMD (every controller runs them with
        # identical inputs — callers must submit the identical request
        # sequence on every process, the standard multi-controller
        # discipline), but the halted plane is batch-sharded, so the
        # egress trigger needs a tiny all-gather to keep the
        # finished-slot list — and with it the slot bookkeeping —
        # consistent across controllers; result rows then land only on
        # the host that owns the slot (per-host shard-local egress).
        self._nproc = len({d.process_index
                           for d in self.mesh.devices.flat})
        self._local_slots = (
            {s for a, b in degress.local_spans(self.mesh, self.slots)
             for s in range(a, b)}
            if self._nproc > 1 else set(range(self.slots)))
        self._halted_gather = (degress.make_halted_gather(self.mesh)
                               if self._nproc > 1 else None)
        # THE resident executable: structural key only (scenario plane
        # armed), built once — every admission reuses it.
        self._run = sharded.make_sharded_run_fn(
            self.p, self.mesh, self.chunk, engine=self.engine)
        # All slots start as pre-halted knob-default rows: free capacity,
        # observably inert until a scenario is installed.
        # (``fresh_state=False`` is restore()'s internal path: the
        # checkpoint replaces ``_st`` immediately, so the fleet-sized init
        # dispatch + placement here would be dead work.)
        if fresh_state:
            st = self.engine.init_batch(
                self.p, sharded.fleet_seeds(0x5EAF, self.slots))
            st = st.replace(halted=np.ones((self.slots,), bool))
            self._st = mesh_ops.shard_batch(
                self.mesh, sim_ops.dedupe_buffers(st))
        else:
            self._st = None
        self._pending: deque[ScenarioRequest] = deque()
        self._active: dict[int, ScenarioRequest] = {}
        self.requests: dict[str, ScenarioRequest] = {}
        self.results: dict[str, dict] = {}
        # The admission-queue lock: submit()/poll() are the service's
        # operator surface and may run on a different thread than the
        # serve() pump (an NDJSON front-end feeding a resident loop), so
        # every MUTATION of the queue-facing state (_pending, requests,
        # results) holds this RLock — the C2 lock-discipline rule
        # (audit/concurrency_lint.py) pins the registry statically.
        # _active/slot bookkeeping stays serve-loop-private.
        self._qlock = threading.RLock()
        self.chunks_polled = 0
        # Global dispatch counter: every dispatched chunk gets polled by
        # the end of a serve() call, so this equals chunks_polled between
        # calls; mid-loop they differ by the in-flight chunk, and the
        # dispatch-span labels / admit_dispatch indices ride this one
        # (chunks_polled alone would mislabel dispatches issued while a
        # poll is still pending).
        self._dispatched = 0
        self._ids = itertools.count()
        self._t0 = time.perf_counter()
        self._recorder = tstream.TimelineRecorder(
            self.p, total_instances=self.slots, out=out,
            meta=dict({"serve": True, "chunk": self.chunk,
                       "slots": self.slots}, **(meta or {})))
        self._lg = tledger.get()
        self._rid = self._lg.new_run(
            "resident_fleet", devices=self.mesh.size, instances=self.slots,
            pipeline=self._ring_k is None, chunk_steps=self.chunk,
            **({"ring_k": self._ring_k} if self._ring_k is not None
               else {}))

    # ------------------------------------------------------------------
    # Submission / inspection.
    # ------------------------------------------------------------------

    def _now(self) -> float:
        return time.perf_counter() - self._t0

    def submit(self, spec, request_id: str | None = None) -> str:
        """Queue one scenario; returns its request id."""
        if isinstance(spec, dict):
            spec = sc.ScenarioSpec.from_dict(spec)
        # Params-dependent attack validation happens HERE, not at
        # admission: ScenarioSpec's constructor can only grammar-check
        # its attack (it has no params), so a program that violates THIS
        # fleet's contract — too many windows for adv_windows, a target
        # id >= n_nodes, a wrong-sized link matrix, an attack on an
        # adversary=False base — must be rejected as a single bad
        # request now, while the queue is untouched.  Deferred to
        # _admit's plane_row it would raise mid-serve-loop with requests
        # already popped/activated and kill the whole resident fleet.
        spec.adv_rows(self.p)
        with self._qlock:
            if request_id is not None:
                rid = request_id
            else:
                # Skip past restored ids: a resumed service's counter
                # restarts, and a collision would silently overwrite the
                # old result.
                rid = f"r{next(self._ids)}"
                while rid in self.requests:
                    rid = f"r{next(self._ids)}"
            if rid in self.requests:
                raise ValueError(f"duplicate request id {rid!r}")
            req = ScenarioRequest(rid, spec, submitted_t=self._now())
            self._pending.append(req)
            self.requests[rid] = req
        self._emit_request(req, "submitted")
        return rid

    def poll(self, request_id: str) -> dict:
        """Status (and result, once egressed) of one request."""
        req = self.requests[request_id]
        out = {"request_id": request_id, "status": req.status,
               "slot": req.slot, "ttfc_s": req.ttfc_s()}
        if request_id in self.results:
            out["result"] = self.results[request_id]
        return out

    @property
    def pending_count(self) -> int:
        return len(self._pending)

    @property
    def active_count(self) -> int:
        return len(self._active)

    def occupancy(self) -> dict:
        return {"slots": self.slots, "active": len(self._active),
                "free": self.slots - len(self._active),
                "pending": len(self._pending),
                "egressed": len(self.results)}

    def _emit_request(self, req: ScenarioRequest, event: str,
                      **extra) -> None:
        self._recorder.emit({
            "kind": "request", "event": event, "id": req.request_id,
            "t_s": round(self._now(), 6), "slot": req.slot,
            "status": req.status, "ttfc_s": req.ttfc_s(),
            **self.occupancy(), **extra})

    # ------------------------------------------------------------------
    # The resident loop.
    # ------------------------------------------------------------------

    def serve(self, max_chunks: int = MAX_CHUNKS_DEFAULT):
        """Pump the double-buffered chunk loop until the admission queue
        AND the fleet drain (graceful drain), or ``max_chunks`` chunks.
        Safe to call repeatedly — the resident state persists between
        calls (that is the point)."""
        # Pre-loop admission: free capacity is host-known, no fetch.
        # self._st tracks the newest valid handle at every step — the
        # chunk runner and install_rows both DONATE their input, so a
        # stale reference after an exception would point at freed
        # buffers.
        self._st = self._admit(self._st)
        if self._ring_k is not None:
            return self._serve_ring(max_chunks)
        with self._lg.span(tledger.DISPATCH, run=self._rid,
                           chunk=self._dispatched):
            self._st, dg = self._run(self._st)
        self._dispatched += 1
        dispatched = 1
        while dispatched < max_chunks and (self._pending or self._active):
            with self._lg.span(tledger.DISPATCH, run=self._rid,
                               chunk=self._dispatched):
                st_next, dg_next = self._run(self._st)  # dispatch k+1 ...
            self._dispatched += 1
            self._st = st_next
            d = self._poll_one(dg)                # ... then poll chunk k
            dg = dg_next
            dispatched += 1
            self._st = self._boundary(self._st, d)
        d = self._poll_one(dg)                    # the final in-flight chunk
        self._st = self._boundary(self._st, d)
        return self

    def _serve_ring(self, max_chunks: int):
        """The device-wrap serve pump: one SEQUENTIAL outer call retires
        up to ``ring_k`` chunks in-graph (early-exiting when the whole
        fleet halts), the host reads the ``[ring_k, 13]`` digest ring
        once, and admission/egress run at the outer-call boundary on the
        LAST retired chunk's digest.  No double-buffering: the in-graph
        early exit makes speculative dispatch waste up to ring_k no-op
        chunks, and the boundary needs the freshest state anyway."""
        dispatched, oi = 0, 0
        while dispatched < max_chunks and (self._pending or self._active):
            cap = min(self._ring_k, max_chunks - dispatched)
            with self._lg.span(tledger.DISPATCH, run=self._rid,
                               chunk=self._dispatched, outer=oi, cap=cap):
                self._st, ring, retired = self._run(self._st, np.int32(cap))
            with self._lg.span(tledger.POLL, run=self._rid,
                               chunk=self._dispatched, outer=oi,
                               cap=cap) as sp:
                rows, n = sharded._poll_ring(ring, retired)
                sp.attrs["retired"] = n
            self._dispatched += n
            dispatched += n
            oi += 1
            base = self.chunks_polled
            self.chunks_polled += n
            recs = self._recorder.record_ring(
                rows, n,
                steps=[(base + i + 1) * self.chunk for i in range(n)])
            t = self._now()
            # first_chunk stamps exactly like _poll_one: a request's rows
            # have demonstrably run once a chunk at-or-after its
            # admit_dispatch index has been polled — sequential dispatch
            # means every admission has executed by this boundary.
            polled = self.chunks_polled - 1
            for req in self._active.values():
                if (req.first_chunk_t is None and req.admitted_t is not None
                        and polled >= (req.admit_dispatch or 0)):
                    req.first_chunk_t = t
                    self._emit_request(req, "first_chunk")
            self._st = self._boundary(self._st, recs[-1])
        return self

    def drain(self, max_chunks: int = MAX_CHUNKS_DEFAULT) -> dict:
        """Graceful drain: serve until everything queued has egressed;
        returns ``results``."""
        self.serve(max_chunks=max_chunks)
        if self._pending or self._active:
            raise RuntimeError(
                f"drain incomplete after {max_chunks} chunks: "
                f"{len(self._pending)} pending, {len(self._active)} active "
                "(raise max_chunks, or a scenario's max_clock horizon is "
                "effectively unbounded)")
        return self.results

    def _poll_one(self, dg) -> dict:
        """The one blocking [13]-digest fetch per chunk (the run_sharded
        poll contract, same ``_poll_digest`` entry point the
        monkeypatched-device_get tests pin)."""
        with self._lg.span(tledger.POLL, run=self._rid,
                           chunk=self.chunks_polled):
            vec = sharded._poll_digest(dg)
        self.chunks_polled += 1
        row = self._recorder.record(
            vec, steps=self.chunks_polled * self.chunk)
        t = self._now()
        # first_chunk stamps only when a chunk that actually EXECUTED the
        # request's rows has been polled: a boundary admission lands in
        # the in-flight chunk's output, so the poll of that chunk (where
        # the slot still ran halted) must not count.
        polled = self.chunks_polled - 1
        for req in self._active.values():
            if (req.first_chunk_t is None and req.admitted_t is not None
                    and polled >= (req.admit_dispatch or 0)):
                req.first_chunk_t = t
                self._emit_request(req, "first_chunk")
        return row

    def _boundary(self, st, digest_row: dict):
        """Between-chunks work: egress finished slots, admit pending.

        The digest's ``halted`` count is the trigger — only when it says
        some ACTIVE slot halted (halted > free slots) does the host pay
        the one [slots] bool halted-plane fetch that identifies which;
        steady-state chunks stay digest-only."""
        free_before = self.slots - len(self._active)
        # Digest lag: the polled chunk predates any admission issued after
        # its dispatch, so slots admitted since then are still counted
        # halted by this digest — subtract them or every admission wave
        # would trigger one spurious (and pipeline-stalling) halted-plane
        # fetch on the in-flight state.
        polled = self.chunks_polled - 1
        stale = sum(1 for r in self._active.values()
                    if (r.admit_dispatch or 0) > polled)
        finished = int(digest_row["halted"]) - free_before - stale
        if finished > 0 and self._active:
            st = self._egress(st)
        if self._pending and len(self._active) < self.slots:
            st = self._admit(st)
        return st

    # ------------------------------------------------------------------
    # Egress.
    # ------------------------------------------------------------------

    def _egress(self, st):
        with self._lg.span(tledger.EGRESS, run=self._rid):
            if self._halted_gather is not None:
                # Multi-process: the [slots] plane is batch-sharded, and
                # every controller must see the SAME finished-slot list
                # (the _active/_pending bookkeeping is SPMD state) — one
                # replicated all-gather per egress event, outside the
                # chunk loop.
                halted = np.asarray(
                    jax.device_get(self._halted_gather(st.halted)))
            else:
                halted = np.asarray(jax.device_get(st.halted))
            done = [s for s, req in sorted(self._active.items())
                    if bool(halted[s])]
            if not done:
                return st
            if self._halted_gather is not None:
                # Per-host shard-local landing: this controller fetches
                # only its OWN finished rows (O(k) device-side row
                # gathers, never the whole local shard); finished slots
                # owned elsewhere still clear their _active entry (the
                # bookkeeping stays consistent) but their result lands on
                # the owning host's stream/results.
                rows_by_slot = degress.local_rows_at(
                    st, [s for s in done if s in self._local_slots])
                rows = None
            else:
                idx = np.asarray(done, np.int32)
                # Land ONLY the finished rows on host: one gather per
                # leaf over the k finished slots (the unpad discipline —
                # never the whole fleet).
                rows = jax.tree.map(
                    lambda x: np.asarray(jax.device_get(x[idx])), st)
                rows_by_slot = None
            for j, slot in enumerate(done):
                req = self._active.pop(slot)
                # A scenario that halts within its first executed chunk
                # can reach egress (this fetch reads the freshest state)
                # before _poll_one's stamp condition is met — the slot
                # demonstrably ran, so stamp first_chunk here rather than
                # egress a request whose lifecycle says it never started.
                if req.first_chunk_t is None and req.admitted_t is not None:
                    req.first_chunk_t = self._now()
                    self._emit_request(req, "first_chunk")
                req.egressed_t = self._now()
                if rows_by_slot is not None:
                    row = rows_by_slot.get(slot)  # None: another host owns it
                else:
                    row = jax.tree.map(lambda x, jj=j: x[jj], rows)
                if row is not None:
                    res = self._result_of(req, row)
                    with self._qlock:
                        self.results[req.request_id] = res
                self._emit_request(
                    req, "egressed",
                    latency_s=round(req.egressed_t - req.submitted_t, 6),
                    result=self.results.get(req.request_id))
        return st

    def _result_of(self, req: ScenarioRequest, row) -> dict:
        """Per-request result summary from one landed slot row."""
        p = self.p
        eq, silent, forge = req.spec.byz_masks(p)
        byz_any = (np.asarray(eq) | np.asarray(silent) | np.asarray(forge))
        prog = req.spec.attack_program()
        if prog is not None:
            # Nodes a windowed Byzantine behavior can activate are not
            # honest referees: exclude them from the safety check exactly
            # like the static masks.
            from ..adversary import dsl as adsl

            tgts = [t for t in adsl.byz_targets(prog) if t < p.n_nodes]
            byz_any = byz_any | np.isin(np.arange(p.n_nodes), tgts)
        st1 = jax.tree.map(lambda x: np.asarray(x)[None], row)
        safe = bool(byzantine.check_safety_reference(
            st1, honest_mask=~byz_any)[0])
        out = {
            "request_id": req.request_id,
            "spec": req.spec.to_dict(),
            "slot": req.slot,
            "events": int(row.n_events),
            "clock": int(row.clock),
            "commits": [int(c) for c in np.asarray(row.ctx.commit_count)],
            "committed_round_max": int(np.max(np.asarray(row.store.hcr))),
            "msgs_sent": int(row.n_msgs_sent),
            "msgs_dropped": int(row.n_msgs_dropped),
            "safe": safe,
            "ttfc_s": req.ttfc_s(),
        }
        if p.watchdog:
            # Per-request watchdog verdict (telemetry/stream.py WD_SLOTS):
            # the slot's in-graph trip counters — the safety/liveness
            # referee for each admitted (possibly adversarial) scenario;
            # fleet_watch --serve renders these per egressed request.
            wd = np.asarray(row.wd).reshape(-1)
            trips = {name: int(wd[tstream.WD_SLOTS.index(name)])
                     for name in tstream.WD_DETECTORS}
            out["watchdog"] = dict(
                trips,
                safety_ok=(trips["safety_conflict"] == 0
                           and trips["round_regress"] == 0),
                liveness_ok=trips["stall"] == 0)
        if prog is not None:
            # The decoded attack program rides the result row — the
            # counterexample-reporting contract (what exactly was this
            # slot subjected to, independent of the request file).
            out["attack"] = prog.host_plane(p).describe()
        if p.telemetry:
            from ..telemetry import report as tel_report

            out["telemetry"] = tel_report.metrics_dict(p, row)
        return out

    # ------------------------------------------------------------------
    # Admission.
    # ------------------------------------------------------------------

    def _admit(self, st):
        """Install up to free-slot-count pending scenarios: fresh init
        rows assembled host-side into a fleet-shaped donor, then ONE
        batched donated device write (scenario.install_rows) — the
        resident executable is never rebuilt.

        The donor is deliberately FLEET-shaped (not k admitted rows): a
        k-sized donor would bake k into the install executable's shape
        key and recompile per distinct admission width, trading a
        bounded [B]-sized H2D copy per admission wave for exactly the
        per-config compile storm this subsystem exists to kill."""
        free = [s for s in range(self.slots) if s not in self._active]
        with self._qlock:
            k = min(len(free), len(self._pending))
            if k == 0:
                return st
            taken = [self._pending.popleft() for _ in range(k)]
        with self._lg.span(tledger.ADMIT, run=self._rid, requests=k):
            mask = np.zeros((self.slots,), bool)
            donor = None
            admitted = []
            for slot, req in zip(free[:k], taken):
                req.slot = slot
                row_st = jax.tree.map(
                    lambda x: np.asarray(jax.device_get(x)),
                    sc.init_slot(self.p, req.spec.plane_row(self.p),
                                 engine=self.engine))
                if donor is None:
                    donor = jax.tree.map(
                        lambda x: np.zeros((self.slots,) + x.shape,
                                           x.dtype), row_st)

                def place(d, r, s=slot):
                    d[s] = r
                    return d

                donor = jax.tree.map(place, donor, row_st)
                mask[slot] = True
                self._active[slot] = req
                admitted.append(req)
            donor = mesh_ops.shard_batch(self.mesh, donor)
            mask_dev = mesh_ops.shard_batch(self.mesh, mask)
            st = sc.install_rows(st, mask_dev, donor)
            t = self._now()
            for req in admitted:
                req.admitted_t = t
                req.admit_dispatch = self._dispatched
                self._emit_request(req, "admitted")
        return st

    # ------------------------------------------------------------------
    # Checkpoint-based preemption / eviction.
    # ------------------------------------------------------------------

    def save(self, path: str) -> None:
        """Preemption-safe eviction: the resident device state checkpoints
        through sim/checkpoint.py and the serve bookkeeping (slot table,
        pending specs, finished results) lands in a JSON sidecar — a
        preempted service resumes with :meth:`ResidentFleet.restore` and
        every live slot continues bit-identically (the checkpoint
        round-trip guarantee)."""
        from ..sim import checkpoint as ckpt

        if self._nproc > 1:
            raise NotImplementedError(
                "ResidentFleet.save on a multi-process mesh: preemption "
                "checkpoints of a pod-resident service need the per-host "
                "shard path (distributed.egress.save_shards) plus a "
                "host-0 sidecar merge — run the service single-process "
                "to preempt/resume, or checkpoint the underlying fleet "
                "with distributed.egress")
        host = jax.tree.map(lambda x: np.asarray(jax.device_get(x)),
                            self._st)
        ckpt.save(path, host)

        def req_dict(r: ScenarioRequest) -> dict:
            return {"request_id": r.request_id, "spec": r.spec.to_dict(),
                    "slot": r.slot, "status": r.status}

        # Snapshot the queue-facing state under the admission lock: an
        # operator thread may be submit()ing while eviction saves, and an
        # unlocked deque iteration raises (or the sidecar lands torn).
        with self._qlock:
            side = {
                "serve_version": tschema.SERVE_VERSION,
                "slots": self.slots,
                "chunk": self.chunk,
                # Informational (additive, no version bump): the dispatch
                # wrap is NOT pinned by the checkpoint — chunk state is
                # wrap-independent, so a service saved under one wrap
                # resumes bit-identically under either (the restore
                # params decide; tests/test_checkpoint.py pins the
                # cross-wrap resume for the underlying fleet).
                "ring_k": self._ring_k,
                "chunks_polled": self.chunks_polled,
                "active": {str(s): req_dict(r)
                           for s, r in self._active.items()},
                "pending": [req_dict(r) for r in self._pending],
                "results": dict(self.results),
            }
        with open(path + ".serve.json", "w") as f:
            json.dump(side, f, indent=1)

    @classmethod
    def restore(cls, path: str, p: SimParams, mesh=None, engine=None,
                out=None) -> "ResidentFleet":
        """Resume a preempted service from :meth:`save`'s artifact pair."""
        from ..sim import checkpoint as ckpt

        with open(path + ".serve.json") as f:
            side = json.load(f)
        tschema.require_serve_version(side.get("serve_version"),
                                      what=f"{path}.serve.json")
        svc = cls(p, slots=side["slots"], mesh=mesh, chunk=side["chunk"],
                  engine=engine, out=out, fresh_state=False)
        # Host-restore + device_put placement (NOT checkpoint.load_sharded's
        # make_array_from_callback path): the resident executable is
        # usually an AOT-store load, and on this toolchain a DESERIALIZED
        # executable aborts the process when dispatched on
        # callback-constructed arrays — device_put-placed inputs (exactly
        # how a fresh fleet is placed) are the supported form.  A service
        # state is one slots-sized fleet, so the host staging copy
        # load_sharded exists to avoid is immaterial here.
        like = jax.eval_shape(
            lambda: svc.engine.init_batch(
                svc.p, np.zeros(side["slots"], np.uint32)))
        host = ckpt.load(path, svc.p, like=like)
        # dedupe_buffers before placement, exactly like fresh init: a bare
        # device_put of host numpy can ZERO-COPY alias the numpy memory on
        # the CPU backend, and the chunk runner donates its input — XLA
        # then recycles memory it doesn't own (observed: segfault on the
        # second post-restore dispatch under the persistent compile
        # cache).  The copy forces every leaf into an XLA-owned buffer.
        svc._st = mesh_ops.shard_batch(
            svc.mesh, sim_ops.dedupe_buffers(host))
        svc.chunks_polled = int(side.get("chunks_polled", 0))
        svc._dispatched = svc.chunks_polled
        svc.results = dict(side.get("results", {}))
        # Egressed requests re-register too (their spec rides the saved
        # result): poll() keeps answering for them after a resume, and
        # the submit()/auto-id duplicate guards see their ids — otherwise
        # a post-resume submission could silently overwrite an old result.
        for rid, res in svc.results.items():
            req = ScenarioRequest(
                rid, sc.ScenarioSpec.from_dict(res["spec"]),
                slot=res.get("slot"), admitted_t=0.0, first_chunk_t=0.0,
                egressed_t=0.0)
            svc.requests[rid] = req
        for s, rd in side.get("active", {}).items():
            req = ScenarioRequest(
                rd["request_id"], sc.ScenarioSpec.from_dict(rd["spec"]),
                slot=int(s), admitted_t=0.0, first_chunk_t=0.0)
            svc._active[int(s)] = req
            svc.requests[req.request_id] = req
        for rd in side.get("pending", []):
            req = ScenarioRequest(
                rd["request_id"], sc.ScenarioSpec.from_dict(rd["spec"]))
            svc._pending.append(req)
            svc.requests[req.request_id] = req
        return svc

    def close(self) -> None:
        self._recorder.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
