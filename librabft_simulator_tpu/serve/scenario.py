"""Per-slot scenario planes: compile-time knobs re-expressed as traced data.

The batch dimension already multiplies *instances*; this module makes it
multiply *scenarios*.  Every per-instance knob that used to be a static
``SimParams`` compile key — the delay distribution (its quantile table
becomes a per-slot ``[T]`` int32 row, ``SimState.sc_delay``), the commit
rule (a per-slot 2-vs-3-chain selector, ``SimState.sc_commit``, consumed
by the traced select in core/store.py via ``types.TracedParams``), the
Byzantine schedule (``sim/byzantine.py`` ``SCHEDULES``, realized as the
three per-instance masks the engines already carry), drop rate, rng seed,
and horizon — is carried in one fixed-shape :class:`ScenarioPlane` row per
slot.  With ``SimParams.scenario=True`` the engines read these rows instead
of the static knobs, so:

* the structural compile key shrinks to shapes + engine flavor
  (``SimParams.structural()`` normalizes ``commit_chain`` out; the sharded
  runner stops keying on delay fields) — ONE executable serves the whole
  scenario family, which collapses the AOT executable store;
* installing a new scenario into a fleet slot is a device write
  (:func:`install_rows` — a single batched donated dispatch of pure
  elementwise selects; R1/R2-clean, no recompile), which is what the
  resident fleet service's admission queue runs on.

Per-slot trajectories are bit-identical to a dedicated static run of the
same scenario (tests/test_serve.py; FUZZ_SCENARIO campaigns), because every
knob's effect routes through the same value path — the plane only changes
WHERE the value comes from.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..adversary import dsl as adsl
from ..adversary import plane as aplane
from ..core.types import SimParams
from ..sim import byzantine
from ..sim import simulator as sim_ops
from ..utils import hashing as H
from ..utils.quantile import TABLE_BITS

I32 = jnp.int32


@struct.dataclass
class ScenarioPlane:
    """One scenario per row: the traced per-slot knob tensors.

    Unbatched rows describe one slot; a leading ``[B]`` dim describes a
    fleet.  All int/uint/bool by design (the R2 discipline)."""

    seed: jnp.ndarray            # uint32 instance rng stream
    delay_table: jnp.ndarray     # [T] int32 delay quantile table
    drop_u32: jnp.ndarray        # uint32 drop threshold
    max_clock: jnp.ndarray       # int32 horizon
    commit_chain: jnp.ndarray    # int32: 2 (HotStuff-style) | 3 (LibraBFTv2)
    byz_equivocate: jnp.ndarray  # [N] bool
    byz_silent: jnp.ndarray      # [N] bool
    byz_forge_qc: jnp.ndarray    # [N] bool
    # Adversary-plane rows (adversary/; zero-width when the base params'
    # adversary knob is off): the slot's lowered attack program.
    adv_sched: jnp.ndarray       # [W, ADV_FIELDS] int32
    adv_link: jnp.ndarray        # [N, N] int32
    adv_group: jnp.ndarray       # [N] int32
    adv_heal: jnp.ndarray        # [1] int32


#: The scenario-settable SimParams fields a spec overrides on its base
#: (everything else — shapes, engine lowering — is structural and shared).
_SPEC_PARAM_FIELDS = ("delay_kind", "delay_mean", "delay_variance",
                      "delay_pareto_scale", "delay_pareto_alpha",
                      "drop_prob", "commit_chain", "max_clock")


@dataclasses.dataclass(frozen=True)
class ScenarioSpec:
    """A host-side scenario description — the request payload unit.

    ``to_params(base)`` gives the *dedicated-run equivalent*: the static
    ``SimParams`` a batch-mode run of exactly this scenario would use
    (scenario plane off) — the oracle/parity reference every per-slot
    pin compares against.  ``plane_row(base)`` gives the traced form."""

    delay_kind: str = "lognormal"
    delay_mean: float = 10.0
    delay_variance: float = 4.0
    delay_pareto_scale: float = 5.0
    delay_pareto_alpha: float = 1.5
    drop_prob: float = 0.0
    commit_chain: int = 3
    max_clock: int = 1000
    byz_kind: str = "honest"      # one of sim/byzantine.SCHEDULES
    byz_f: int = 0
    byz_authors: tuple | None = None
    seed: int = 0
    #: Attack program (adversary/dsl.py, the ``AttackProgram.from_dict``
    #: grammar), admissible only on an adversary-armed base (the adv_*
    #: plane leaves are zero-width otherwise).  None = the quiet program.
    attack: dict | None = None

    def __post_init__(self):
        if self.byz_kind not in byzantine.SCHEDULES:
            raise ValueError(
                f"unknown Byzantine schedule {self.byz_kind!r}; want one "
                f"of {byzantine.SCHEDULES}")
        if self.commit_chain not in (2, 3):
            raise ValueError(
                f"commit_chain must be 2 or 3, got {self.commit_chain}")
        if self.attack is not None:
            # Grammar check at construction (params-dependent checks —
            # capacities, node ids — run at plane_row lowering time).
            adsl.AttackProgram.from_dict(self.attack)

    @classmethod
    def from_dict(cls, d: dict) -> "ScenarioSpec":
        """Build from an NDJSON request row; unknown keys fail loud (a
        typo'd knob must not silently run the default scenario)."""
        known = {f.name for f in dataclasses.fields(cls)}
        extra = set(d) - known
        if extra:
            raise ValueError(
                f"unknown scenario field(s) {sorted(extra)}; known: "
                f"{sorted(known)}")
        if "byz_authors" in d and d["byz_authors"] is not None:
            d = dict(d, byz_authors=tuple(d["byz_authors"]))
        return cls(**d)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def to_params(self, base: SimParams) -> SimParams:
        """The static params of a dedicated batch-mode run of this
        scenario (scenario plane OFF — the bit-parity reference)."""
        return dataclasses.replace(
            base, scenario=False,
            **{f: getattr(self, f) for f in _SPEC_PARAM_FIELDS})

    def byz_masks(self, base: SimParams):
        return byzantine.schedule_masks(
            base, self.byz_kind, self.byz_f,
            list(self.byz_authors) if self.byz_authors is not None else None)

    def attack_program(self) -> "adsl.AttackProgram | None":
        """The parsed attack program (None = quiet)."""
        return (adsl.AttackProgram.from_dict(self.attack)
                if self.attack is not None else None)

    def adv_rows(self, base: SimParams) -> dict:
        """The lowered adversary-plane rows of this scenario (inert rows
        when no attack; loud error on an attack without the plane)."""
        prog = self.attack_program()
        if prog is None:
            return aplane.default_rows(base)
        if not base.adversary:
            raise ValueError(
                "scenario carries an attack program but the base params "
                "have adversary=False — arm SimParams.adversary on the "
                "fleet's base config (the adv_* plane leaves are "
                "zero-width otherwise)")
        return prog.lower(base)

    def plane_row(self, base: SimParams) -> ScenarioPlane:
        """This scenario as one (unbatched) plane row."""
        ded = self.to_params(base)
        eq, silent, forge = self.byz_masks(base)
        adv = self.adv_rows(base)
        return ScenarioPlane(
            seed=jnp.uint32(self.seed & 0xFFFFFFFF),
            delay_table=jnp.asarray(ded.delay_table(), I32),
            drop_u32=jnp.uint32(ded.drop_u32),
            max_clock=jnp.asarray(ded.max_clock, I32),
            commit_chain=jnp.asarray(ded.commit_chain, I32),
            byz_equivocate=eq, byz_silent=silent, byz_forge_qc=forge,
            adv_sched=jnp.asarray(adv["adv_sched"]),
            adv_link=jnp.asarray(adv["adv_link"]),
            adv_group=jnp.asarray(adv["adv_group"]),
            adv_heal=jnp.asarray(adv["adv_heal"]),
        )


def default_row(p: SimParams, seed: int | jnp.ndarray = 0) -> ScenarioPlane:
    """The knob-default row: the scenario the base params themselves
    describe (a fleet of these is bit-identical to a plain static run)."""
    n = p.n_nodes
    z = jnp.zeros((n,), jnp.bool_)
    adv = aplane.default_rows(p)
    return ScenarioPlane(
        seed=jnp.asarray(seed).astype(jnp.uint32),
        delay_table=jnp.asarray(p.delay_table(), I32),
        drop_u32=jnp.uint32(p.drop_u32),
        max_clock=jnp.asarray(p.max_clock, I32),
        commit_chain=jnp.asarray(p.commit_chain, I32),
        byz_equivocate=z, byz_silent=z, byz_forge_qc=z,
        adv_sched=jnp.asarray(adv["adv_sched"]),
        adv_link=jnp.asarray(adv["adv_link"]),
        adv_group=jnp.asarray(adv["adv_group"]),
        adv_heal=jnp.asarray(adv["adv_heal"]),
    )


def stack_rows(rows) -> ScenarioPlane:
    """Stack unbatched rows into a ``[B]``-leading plane."""
    rows = list(rows)
    if not rows:
        raise ValueError("stack_rows needs at least one scenario row")
    return jax.tree.map(lambda *xs: jnp.stack(xs), *rows)


def _require_scenario(p: SimParams) -> None:
    if not p.scenario:
        raise ValueError(
            "scenario-plane state needs SimParams.scenario=True (the "
            "sc_delay/sc_commit leaves are zero-width otherwise); arm it "
            "with dataclasses.replace(p, scenario=True)")


def init_slot(p: SimParams, row: ScenarioPlane, engine=None):
    """Fresh engine state for ONE slot running ``row``'s scenario.

    Exactly :func:`sim.simulator.init_state` (or the lane engine's) for
    the scenario's dedicated params: the startup-time draws replay the
    same formula against the ROW's delay table, and the row's knobs land
    in the state leaves the step actually reads (max_clock / drop_u32 /
    byz masks were per-instance state already; sc_delay / sc_commit are
    the new traced rows).  jit/vmap-friendly — :func:`init_rows` vmaps it,
    and the admission path calls it per request."""
    _require_scenario(p)
    eng = engine if engine is not None else sim_ops
    st = eng.init_state(
        p, row.seed,
        byz_equivocate=row.byz_equivocate,
        byz_silent=row.byz_silent,
        byz_forge_qc=row.byz_forge_qc)
    seed = jnp.asarray(row.seed).astype(jnp.uint32)
    draws = jax.vmap(lambda c: H.rng_u32(seed, c.astype(jnp.uint32)))(
        jnp.arange(p.n_nodes))
    startup = (row.delay_table[(draws >> (32 - TABLE_BITS)).astype(I32)]
               + 1).astype(I32)
    return st.replace(
        startup=startup,
        timer_time=startup,
        max_clock=jnp.asarray(row.max_clock, I32),
        drop_u32=jnp.asarray(row.drop_u32, jnp.uint32),
        sc_delay=jnp.asarray(row.delay_table, I32),
        sc_commit=jnp.reshape(jnp.asarray(row.commit_chain, I32), (1,)),
        adv_sched=jnp.asarray(row.adv_sched, I32),
        adv_link=jnp.asarray(row.adv_link, I32),
        adv_group=jnp.asarray(row.adv_group, I32),
        adv_heal=jnp.asarray(row.adv_heal, I32),
    )


def init_rows(p: SimParams, plane: ScenarioPlane, engine=None):
    """Batched heterogeneous fleet: one engine state per plane row."""
    _require_scenario(p)
    return jax.vmap(lambda r: init_slot(p, r, engine=engine))(plane)


def init_specs(p: SimParams, specs, seeds=None, engine=None):
    """Heterogeneous fleet straight from :class:`ScenarioSpec`s (seeds
    default to each spec's own ``seed`` field)."""
    specs = list(specs)
    rows = [s.plane_row(p) for s in specs]
    if seeds is not None:
        rows = [r.replace(seed=jnp.uint32(int(sd) & 0xFFFFFFFF))
                for r, sd in zip(rows, seeds)]
    return init_rows(p, stack_rows(rows), engine=engine)


@functools.partial(jax.jit, donate_argnums=(0,))
def install_rows(st, mask, donor):
    """THE admission write: replace the masked slots of a batched fleet
    state with the donor's rows — one dispatched program, input donated
    (the resident state is threaded in place), and every leaf write a
    pure broadcast-select (``where(mask, donor, old)``): no scatters, no
    gathers, int-only — the R1/R2-clean form by construction, and it
    shards trivially when ``st``/``donor`` are dp-sharded (elementwise on
    matching shardings; no resharding inserted).

    ``mask``: ``[B]`` bool (True = install).  ``donor``: a fleet-shaped
    state tree whose masked rows hold the freshly initialised admitted
    scenarios (unmasked rows are ignored).  Halted slots are observably
    inert (every engine write is live-gated), so installing over them
    between chunks never perturbs live slots — pinned bit-exactly by
    tests/test_serve.py."""
    def put(old, new):
        m = mask.reshape((mask.shape[0],) + (1,) * (old.ndim - 1))
        return jnp.where(m, new, old)

    return jax.tree.map(put, st, donor)
