"""Pure-Python oracle: an independent interpreter of the exact integer
semantics of the tensorized simulator.

Used by ``tests/test_parity.py`` to check that the jitted JAX path produces
bit-identical trajectories (the north-star "commit sequences byte-identical to
the CPU simulator", BASELINE.json).  Everything is plain Python ints masked to
32 bits — no numpy in the hot loop, no JAX.

The oracle deliberately models the *same windowed-table design* as the tensor
path (round-windowed [W, V] record tables, fixed-capacity queue, single timer
slot per node): the window is part of the protocol-variant semantics (records
outside it are rejected), so parity requires modeling it.  Reference
counterparts are cited in the tensor modules; this file cites those.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from ..core.types import (
    ELECTION_CLOSED,
    ELECTION_ONGOING,
    ELECTION_WON,
    KIND_NOTIFY,
    KIND_REQUEST,
    KIND_RESPONSE,
    KIND_TIMER,
    SimParams,
)
from ..sim.simulator import EQUIV_SALT
from ..utils.quantile import TABLE_BITS

M32 = 0xFFFFFFFF
NEVER = 2**31 - 1

# -- hashing (mirrors utils/hashing.py) -------------------------------------

TAG_BLOCK = 0x9E3779B1
TAG_VOTE = 0x85EBCA77
TAG_QC = 0xC2B2AE3D
TAG_TIMEOUT = 0x27D4EB2F
TAG_STATE = 0x165667B1
TAG_EPOCH = 0x5851F42D
TAG_LEADER = 0x2545F491
TAG_SEED = 0x9E447687


def mix32(h: int, x: int) -> int:
    h = (h ^ (x & M32)) & M32
    h = (h * 0x9E3779B1) & M32
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & M32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & M32
    h ^= h >> 16
    return h


def fold(*words: int) -> int:
    h = 0x811C9DC5
    for w in words:
        h = mix32(h, w)
    return h


def rng_u32(seed: int, counter: int) -> int:
    return fold(TAG_SEED, seed, counter)


def state_tag_next(prev_tag, cmd_proposer, cmd_index, time):
    return fold(TAG_STATE, prev_tag, cmd_proposer & M32, cmd_index & M32, time & M32)


def epoch_initial_tag(epoch_id: int) -> int:
    return fold(TAG_EPOCH, epoch_id & M32)


def initial_state_tag() -> int:
    return fold(TAG_STATE, 0)


# -- configuration (mirrors core/config.py) ----------------------------------


def quorum_threshold(weights) -> int:
    return 2 * sum(weights) // 3 + 1


def mask_weight(n, weights, lo, hi):
    """(weight, authors_known) of a (lo, hi) author-bit mask — mirrors
    core/store.py::mask_weight."""
    w = 0
    for a in range(n):
        bit = (lo >> a) & 1 if a < 32 else (hi >> (a - 32)) & 1
        if bit:
            w += weights[a]
    if n >= 64:
        known = True
    elif n >= 32:
        known = (hi >> (n - 32)) == 0
    else:
        known = (lo >> n) == 0 and hi == 0
    return w, known


def pick_author(weights, seed_u32: int) -> int:
    target = (seed_u32 & M32) % sum(weights)
    cum = 0
    for i, w in enumerate(weights):
        cum += w
        if cum > target:
            return i
    return len(weights) - 1


def leader_of_round(weights, round_: int) -> int:
    return pick_author(weights, fold(TAG_LEADER, round_ & M32))


# -- wire structs ------------------------------------------------------------


@dataclasses.dataclass
class BlockMsg:
    valid: bool = False
    round: int = 0
    author: int = 0
    prev_round: int = 0
    prev_tag: int = 0
    time: int = 0
    cmd_proposer: int = 0
    cmd_index: int = 0
    tag: int = 0


@dataclasses.dataclass
class QcMsg:
    valid: bool = False
    epoch: int = 0
    round: int = 0
    blk_tag: int = 0
    state_depth: int = 0
    state_tag: int = 0
    commit_valid: bool = False
    commit_depth: int = 0
    commit_tag: int = 0
    votes_lo: int = 0   # author-bit mask of the aggregated votes (0..31)
    votes_hi: int = 0   # authors 32..63
    author: int = 0
    tag: int = 0


@dataclasses.dataclass
class VoteMsg:
    valid: bool = False
    epoch: int = 0
    round: int = 0
    blk_tag: int = 0
    state_depth: int = 0
    state_tag: int = 0
    commit_valid: bool = False
    commit_depth: int = 0
    commit_tag: int = 0
    author: int = 0


@dataclasses.dataclass
class TimeoutsMsg:
    round: int = 0
    valid: List[bool] = dataclasses.field(default_factory=list)
    hcbr: List[int] = dataclasses.field(default_factory=list)

    @classmethod
    def empty(cls, n):
        return cls(0, [False] * n, [0] * n)


@dataclasses.dataclass
class Payload:
    epoch: int = 0
    hcc: QcMsg = dataclasses.field(default_factory=QcMsg)
    hqc: QcMsg = dataclasses.field(default_factory=QcMsg)
    hcc_blk: BlockMsg = dataclasses.field(default_factory=BlockMsg)
    prop_blk: BlockMsg = dataclasses.field(default_factory=BlockMsg)
    vote: VoteMsg = dataclasses.field(default_factory=VoteMsg)
    tc_to: TimeoutsMsg = dataclasses.field(default_factory=lambda: TimeoutsMsg.empty(0))
    cur_to: TimeoutsMsg = dataclasses.field(default_factory=lambda: TimeoutsMsg.empty(0))
    chain_blk: List[BlockMsg] = dataclasses.field(default_factory=list)
    chain_qc: List[QcMsg] = dataclasses.field(default_factory=list)
    req_hqc_round: int = 0
    req_hcr: int = 0

    @classmethod
    def empty(cls, n, k):
        return cls(
            tc_to=TimeoutsMsg.empty(n), cur_to=TimeoutsMsg.empty(n),
            chain_blk=[BlockMsg() for _ in range(k)],
            chain_qc=[QcMsg() for _ in range(k)],
        )


# -- record store (mirrors core/store.py) ------------------------------------


class Store:
    def __init__(self, p: SimParams):
        self.p = p
        W, V, N = p.window, p.variants, p.n_nodes
        z = lambda: [[0] * V for _ in range(W)]  # noqa: E731
        zb = lambda: [[False] * V for _ in range(W)]  # noqa: E731
        self.blk_valid = zb(); self.blk_round = z(); self.blk_author = z()
        self.blk_prev_round = z(); self.blk_prev_tag = z(); self.blk_time = z()
        self.blk_cmd_proposer = z(); self.blk_cmd_index = z(); self.blk_tag = z()
        self.qc_valid = zb(); self.qc_round = z(); self.qc_blk_var = z()
        self.qc_state_depth = z(); self.qc_state_tag = z()
        self.qc_commit_valid = zb(); self.qc_commit_depth = z()
        self.qc_commit_tag = z(); self.qc_votes_lo = z(); self.qc_votes_hi = z()
        self.qc_author = z(); self.qc_tag = z()
        self.vt_valid = [False] * N; self.vt_blk_var = [0] * N
        self.vt_state_depth = [0] * N; self.vt_state_tag = [0] * N
        self.vt_commit_valid = [False] * N; self.vt_commit_depth = [0] * N
        self.vt_commit_tag = [0] * N
        self.bal_used = [[False, False] for _ in range(V)]
        self.bal_weight = [[0, 0] for _ in range(V)]
        self.bal_state_depth = [[0, 0] for _ in range(V)]
        self.bal_state_tag = [[0, 0] for _ in range(V)]
        self.to_valid = [False] * N; self.to_hcbr = [0] * N; self.to_weight = 0
        self.tc_valid = [False] * N; self.tc_hcbr = [0] * N
        self.epoch_id = 0
        self.initial_round = 0
        self.initial_tag = epoch_initial_tag(0)
        self.initial_state_depth = 0
        self.initial_state_tag = initial_state_tag()
        self.current_round = 1
        self.proposed_var = -1
        self.election = ELECTION_ONGOING
        self.won_var = 0
        self.won_slot = 0
        self.hqc_round = 0; self.hqc_var = 0; self.htc_round = 0
        self.hcr = 0
        self.hcc_valid = False; self.hcc_round = 0; self.hcc_var = 0
        self.anchored = False

    # -- lookups
    def _slot(self, r):
        return r % self.p.window

    def blk_find(self, r, tag):
        sl = self._slot(r)
        for v in range(self.p.variants):
            if self.blk_valid[sl][v] and self.blk_round[sl][v] == r \
                    and self.blk_tag[sl][v] == tag:
                return v
        return -1

    def qc_find(self, r, tag):
        sl = self._slot(r)
        for v in range(self.p.variants):
            if self.qc_valid[sl][v] and self.qc_round[sl][v] == r \
                    and self.qc_tag[sl][v] == tag:
                return v
        return -1

    def hqc_ref(self):
        if self.hqc_round > self.initial_round:
            return self.hqc_round, self.qc_tag[self._slot(self.hqc_round)][self.hqc_var]
        return self.hqc_round, self.initial_tag

    def prev_qc_of_block(self, r, var):
        sl = self._slot(r)
        pr = self.blk_prev_round[sl][var]
        pt = self.blk_prev_tag[sl][var]
        if pr == self.initial_round and pt == self.initial_tag:
            return True, pr, -1
        v = self.qc_find(pr, pt)
        return v >= 0, pr, v

    def qc_walk_back(self, start_valid, start_round, start_var, steps):
        """Per-hop (valid, round, var, hit_initial), newest first."""
        out = []
        alive = bool(start_valid) and start_round > self.initial_round
        r, v = start_round, start_var
        for _ in range(steps):
            bvar = self.qc_blk_var[self._slot(r)][v]
            found, pr, pv = self.prev_qc_of_block(r, bvar)
            hit = alive and found and pv < 0
            out.append((alive, r, v, hit))
            alive2 = alive and found and pv >= 0
            if alive2:
                r, v = pr, pv
            alive = alive2
        return out

    def previous_round(self, r, var):
        return self.blk_prev_round[self._slot(r)][var]

    def second_previous_round(self, r, var):
        found, pr, pv = self.prev_qc_of_block(r, var)
        if pv < 0 or not found:
            return self.initial_round
        bvar = self.qc_blk_var[self._slot(pr)][pv]
        return self.blk_prev_round[self._slot(pr)][bvar]

    def vote_committed_state(self, blk_round, blk_var):
        C = self.p.commit_chain
        found0, pr, pv = self.prev_qc_of_block(blk_round, blk_var)
        hops = self.qc_walk_back(found0 and pv >= 0, pr, max(pv, 0), C - 1)
        ok = True
        prev_r = blk_round
        for i in range(C - 1):
            ok = ok and hops[i][0] and prev_r == hops[i][1] + 1
            prev_r = hops[i][1]
        touched = (found0 and pv < 0) or any(h[3] for h in hops[: C - 1])
        undet = self.anchored and touched
        last = hops[C - 2]
        sl = self._slot(last[1])
        d = self.qc_state_depth[sl][last[2]]
        t = self.qc_state_tag[sl][last[2]]
        return (ok, d if ok else 0, t if ok else 0, undet)

    def compute_state(self, blk_round, blk_var):
        found, pr, pv = self.prev_qc_of_block(blk_round, blk_var)
        if pv < 0:
            base_d, base_t = self.initial_state_depth, self.initial_state_tag
        else:
            sl = self._slot(pr)
            base_d = self.qc_state_depth[sl][pv]
            base_t = self.qc_state_tag[sl][pv]
        sl = self._slot(blk_round)
        tag = state_tag_next(
            base_t, self.blk_cmd_proposer[sl][blk_var],
            self.blk_cmd_index[sl][blk_var], self.blk_time[sl][blk_var],
        )
        return found, base_d + 1, tag

    def update_commit_chain(self, qc_round, qc_var):
        C = self.p.commit_chain
        hops = self.qc_walk_back(True, qc_round, qc_var, C)
        ok = True
        for i in range(C):
            ok = ok and hops[i][0]
            if i > 0:
                ok = ok and hops[i - 1][1] == hops[i][1] + 1
        r1 = hops[C - 1][1]
        ok = ok and r1 > self.hcr
        if ok:
            self.hcr = r1
            self.hcc_valid = True
            self.hcc_round = qc_round
            self.hcc_var = qc_var

    def update_current_round(self, r):
        if r > self.current_round:
            N, V = self.p.n_nodes, self.p.variants
            self.current_round = r
            self.proposed_var = -1
            self.vt_valid = [False] * N
            self.to_valid = [False] * N  # to_hcbr kept stale, like the tensor path
            self.to_weight = 0
            self.bal_used = [[False, False] for _ in range(V)]
            self.bal_weight = [[0, 0] for _ in range(V)]
            self.bal_state_depth = [[0, 0] for _ in range(V)]
            self.bal_state_tag = [[0, 0] for _ in range(V)]
            self.election = ELECTION_ONGOING
            self.won_var = 0
            self.won_slot = 0

    def _pick_variant(self, valid_col, round_col, tag_col, r, tag):
        stale0 = (not valid_col[0]) or round_col[0] != r
        stale1 = (not valid_col[1]) or round_col[1] != r
        dup0 = (not stale0) and tag_col[0] == tag
        dup1 = (not stale1) and tag_col[1] == tag
        is_dup = dup0 or dup1
        var = 0 if stale0 else (1 if stale1 else -1)
        return var, is_dup, var >= 0

    # -- insertions
    def insert_block(self, weights, b: BlockMsg, rec_epoch):
        p = self.p
        sl = self._slot(b.round)
        var, is_dup, has_room = self._pick_variant(
            self.blk_valid[sl], self.blk_round[sl], self.blk_tag[sl], b.round, b.tag)
        prev_initial = b.prev_round == self.initial_round and b.prev_tag == self.initial_tag
        prev_known = prev_initial or self.qc_find(b.prev_round, b.prev_tag) >= 0
        in_window = b.round > self.current_round - p.window
        ok = (b.valid and rec_epoch == self.epoch_id and not is_dup and has_room
              and prev_known and b.round > b.prev_round and in_window)
        if not ok:
            return False
        var = max(var, 0)
        self.blk_valid[sl][var] = True
        self.blk_round[sl][var] = b.round
        self.blk_author[sl][var] = b.author
        self.blk_prev_round[sl][var] = b.prev_round
        self.blk_prev_tag[sl][var] = b.prev_tag
        self.blk_time[sl][var] = b.time
        self.blk_cmd_proposer[sl][var] = b.cmd_proposer
        self.blk_cmd_index[sl][var] = b.cmd_index
        self.blk_tag[sl][var] = b.tag
        if b.round == self.current_round and \
                leader_of_round(weights, self.current_round) == b.author:
            self.proposed_var = var
        return True

    def insert_vote(self, weights, v: VoteMsg):
        author = min(max(v.author, 0), self.p.n_nodes - 1)
        bvar = self.blk_find(v.round, v.blk_tag)
        cs_ok, cs_d, cs_t, cs_undet = self.vote_committed_state(v.round, max(bvar, 0))
        commit_match = cs_undet or (
            v.commit_valid == cs_ok
            and (not cs_ok or (v.commit_depth == cs_d and v.commit_tag == cs_t)))
        ok = (v.valid and v.epoch == self.epoch_id and bvar >= 0 and commit_match
              and v.round == self.current_round and not self.vt_valid[author])
        if not ok:
            return False
        bvar = max(bvar, 0)
        self.vt_valid[author] = True
        self.vt_blk_var[author] = bvar
        self.vt_state_depth[author] = v.state_depth
        self.vt_state_tag[author] = v.state_tag
        self.vt_commit_valid[author] = v.commit_valid
        self.vt_commit_depth[author] = v.commit_depth
        self.vt_commit_tag[author] = v.commit_tag
        if self.election != ELECTION_ONGOING:
            return True
        m0 = self.bal_used[bvar][0] and self.bal_state_depth[bvar][0] == v.state_depth \
            and self.bal_state_tag[bvar][0] == v.state_tag
        m1 = self.bal_used[bvar][1] and self.bal_state_depth[bvar][1] == v.state_depth \
            and self.bal_state_tag[bvar][1] == v.state_tag
        if m0:
            slot = 0
        elif m1:
            slot = 1
        elif not self.bal_used[bvar][0]:
            slot = 0
        elif not self.bal_used[bvar][1]:
            slot = 1
        else:
            return True
        self.bal_used[bvar][slot] = True
        self.bal_weight[bvar][slot] += weights[author]
        self.bal_state_depth[bvar][slot] = v.state_depth
        self.bal_state_tag[bvar][slot] = v.state_tag
        if self.bal_weight[bvar][slot] >= quorum_threshold(weights):
            self.election = ELECTION_WON
            self.won_var = bvar
            self.won_slot = slot
        return True

    def insert_qc(self, weights, q: QcMsg):
        p = self.p
        sl = self._slot(q.round)
        var, is_dup, has_room = self._pick_variant(
            self.qc_valid[sl], self.qc_round[sl], self.qc_tag[sl], q.round, q.tag)
        bvar = self.blk_find(q.round, q.blk_tag)
        bvar_c = max(bvar, 0)
        author_ok = self.blk_author[sl][bvar_c] == q.author
        cs_ok, cs_d, cs_t, cs_undet = self.vote_committed_state(q.round, bvar_c)
        commit_match = cs_undet or (
            q.commit_valid == cs_ok
            and (not cs_ok or (q.commit_depth == cs_d and q.commit_tag == cs_t)))
        exec_ok, st_d, st_t = self.compute_state(q.round, bvar_c)
        state_match = exec_ok and st_d == q.state_depth and st_t == q.state_tag
        in_window = q.round > self.current_round - p.window
        vote_w, authors_known = mask_weight(p.n_nodes, weights, q.votes_lo,
                                            q.votes_hi)
        quorum_ok = authors_known and vote_w >= quorum_threshold(weights)
        tag_ok = q.tag == fold(
            TAG_QC, q.epoch & M32, q.round & M32, q.blk_tag,
            q.state_depth & M32, q.state_tag, int(q.commit_valid) & M32,
            q.commit_depth & M32, q.commit_tag, q.votes_lo, q.votes_hi,
            q.author & M32)
        ok = (q.valid and q.epoch == self.epoch_id and not is_dup and has_room
              and bvar >= 0 and author_ok and commit_match and state_match
              and in_window and quorum_ok and tag_ok)
        if not ok:
            return False
        var = max(var, 0)
        self.qc_valid[sl][var] = True
        self.qc_round[sl][var] = q.round
        self.qc_blk_var[sl][var] = bvar_c
        self.qc_state_depth[sl][var] = q.state_depth
        self.qc_state_tag[sl][var] = q.state_tag
        self.qc_commit_valid[sl][var] = q.commit_valid
        self.qc_commit_depth[sl][var] = q.commit_depth
        self.qc_commit_tag[sl][var] = q.commit_tag
        self.qc_votes_lo[sl][var] = q.votes_lo
        self.qc_votes_hi[sl][var] = q.votes_hi
        self.qc_author[sl][var] = q.author
        self.qc_tag[sl][var] = q.tag
        if q.round > self.hqc_round:
            self.hqc_round = q.round
            self.hqc_var = var
        self.update_current_round(q.round + 1)
        self.update_commit_chain(q.round, var)
        return True

    def insert_timeout(self, weights, t_epoch, t_round, t_hcbr, t_author):
        author = min(max(t_author, 0), self.p.n_nodes - 1)
        ok = (t_epoch == self.epoch_id and t_hcbr <= self.hqc_round
              and t_round == self.current_round and not self.to_valid[author])
        if not ok:
            return False
        self.to_valid[author] = True
        self.to_hcbr[author] = t_hcbr
        self.to_weight += weights[author]
        if self.to_weight >= quorum_threshold(weights):
            self.tc_valid = list(self.to_valid)
            self.tc_hcbr = list(self.to_hcbr)
            self.htc_round = self.current_round
            self.update_current_round(self.current_round + 1)
        return True

    # -- creation
    def make_block_tag(self, r, author, prev_round, prev_tag, time, cmd_proposer,
                       cmd_index):
        return fold(TAG_BLOCK, self.epoch_id & M32, r & M32, author & M32,
                    prev_round & M32, prev_tag, time & M32, cmd_proposer & M32,
                    cmd_index & M32)

    def propose_block(self, weights, author, prev_round, prev_tag, time, cmd_index):
        b = BlockMsg(
            valid=True, round=self.current_round, author=author,
            prev_round=prev_round, prev_tag=prev_tag, time=time,
            cmd_proposer=author, cmd_index=cmd_index,
            tag=self.make_block_tag(self.current_round, author, prev_round,
                                    prev_tag, time, author, cmd_index),
        )
        return self.insert_block(weights, b, self.epoch_id)

    def create_vote(self, weights, author, blk_round, blk_var):
        sl = self._slot(blk_round)
        cs_ok, cs_d, cs_t, _ = self.vote_committed_state(blk_round, blk_var)
        exec_ok, st_d, st_t = self.compute_state(blk_round, blk_var)
        v = VoteMsg(
            valid=exec_ok, epoch=self.epoch_id, round=blk_round,
            blk_tag=self.blk_tag[sl][blk_var], state_depth=st_d, state_tag=st_t,
            commit_valid=cs_ok, commit_depth=cs_d, commit_tag=cs_t, author=author,
        )
        return self.insert_vote(weights, v) and exec_ok

    def create_timeout(self, weights, author, round_):
        return self.insert_timeout(weights, self.epoch_id, round_, self.hqc_round,
                                   author)

    def has_timeout(self, author, round_):
        return round_ == self.current_round and self.to_valid[max(author, 0)]

    def check_new_qc(self, weights, author):
        if self.election != ELECTION_WON:
            return False
        bvar = self.won_var
        sl = self._slot(self.current_round)
        if self.blk_author[sl][bvar] != author:
            return False
        st_d = self.bal_state_depth[bvar][self.won_slot]
        st_t = self.bal_state_tag[bvar][self.won_slot]
        cs_ok, cs_d, cs_t, _ = self.vote_committed_state(self.current_round, bvar)
        lo = hi = 0
        for i in range(self.p.n_nodes):
            m = (self.vt_valid[i] and self.vt_state_depth[i] == st_d
                 and self.vt_state_tag[i] == st_t and self.vt_blk_var[i] == bvar)
            if m and i < 32:
                lo |= 1 << i
            elif m:
                hi |= 1 << (i - 32)
        tag = fold(TAG_QC, self.epoch_id & M32, self.current_round & M32,
                   self.blk_tag[sl][bvar], st_d & M32, st_t,
                   int(cs_ok) & M32, cs_d & M32, cs_t, lo, hi, author & M32)
        q = QcMsg(
            valid=True, epoch=self.epoch_id, round=self.current_round,
            blk_tag=self.blk_tag[sl][bvar], state_depth=st_d, state_tag=st_t,
            commit_valid=cs_ok, commit_depth=cs_d, commit_tag=cs_t,
            votes_lo=lo, votes_hi=hi, author=author, tag=tag,
        )
        self.election = ELECTION_CLOSED
        self.insert_qc(weights, q)
        return True

    def committed_states_after(self, after_round):
        """Ascending (round, depth, tag), mirroring the tensor version."""
        W = self.p.window
        start_r = self.hcc_round if self.hcc_valid else 0
        hops = self.qc_walk_back(self.hcc_valid, start_r, self.hcc_var, W)
        skip = self.p.commit_chain - 1
        out = []
        for i, (valid, r, v, _) in enumerate(hops):
            if valid and i >= skip and r > after_round:
                sl = self._slot(r)
                out.append((r, self.qc_state_depth[sl][v], self.qc_state_tag[sl][v]))
        return list(reversed(out))
