"""Oracle counterpart of core/{pacemaker,node,data_sync}.py and
sim/simulator.py: the full event loop in plain Python.

Every decision mirrors the tensor path exactly (same rng counters, same
candidate ordering, same queue slot assignment), so whole trajectories are
bit-comparable.  See tests/test_parity.py.
"""

from __future__ import annotations

import copy
import dataclasses
from typing import List

import numpy as np

from ..core.types import (
    KIND_NOTIFY, KIND_REQUEST, KIND_RESPONSE, KIND_TIMER, SimParams,
)
from ..sim.simulator import EQUIV_SALT
from ..utils.quantile import TABLE_BITS
from . import engine as E

NEVER = E.NEVER


@dataclasses.dataclass
class Pacemaker:
    active_epoch: int = 0
    active_round: int = 0
    active_leader: int = -1
    round_start: int = 0
    round_duration: int = 0


@dataclasses.dataclass
class NodeExtra:
    latest_voted_round: int = 0
    locked_round: int = 0
    latest_query_all: int = 0
    tracker_epoch: int = 0
    tracker_hcr: int = 0
    tracker_commit_time: int = 0


class Context:
    def __init__(self, p: SimParams):
        self.p = p
        self.next_cmd_index = 0
        self.commit_count = 0
        self.last_depth = 0
        self.last_tag = E.initial_state_tag()
        self.sync_jumps = 0
        self.skipped_commits = 0
        H = p.commit_log
        self.log_round = [0] * H
        self.log_depth = [0] * H
        self.log_tag = [0] * H


def round_duration(p: SimParams, dur_table, active_round, hcr):
    hccr = hcr + 2 if hcr > 0 else 0
    n = min(max(active_round - hccr, 0), p.dur_table_size - 1)
    return int(dur_table[n])


@dataclasses.dataclass
class PacemakerActions:
    should_propose: bool
    propose_prev_round: int
    propose_prev_tag: int
    should_create_timeout: bool
    timeout_round: int
    send_leader: int
    should_broadcast: bool
    should_query_all: bool
    next_sched: int


def update_pacemaker(p, pm: Pacemaker, s: E.Store, weights, author, epoch_id,
                     latest_query_all, clock, dur_table):
    active_round = max(s.hqc_round, s.htc_round) + 1
    enter = (epoch_id > pm.active_epoch) or (
        epoch_id == pm.active_epoch and active_round > pm.active_round)
    if enter:
        pm.active_epoch = epoch_id
        pm.active_round = active_round
        pm.active_leader = E.leader_of_round(weights, active_round)
        pm.round_start = clock
        pm.round_duration = round_duration(p, dur_table, active_round, s.hcr)
    send_leader = pm.active_leader if (enter and pm.active_leader != author) else -1

    next_sched = NEVER
    has_prop = proposed_block_valid(pm, s)
    hqc_r, hqc_t = s.hqc_ref()
    should_propose = pm.active_leader == author and not has_prop
    should_broadcast = should_propose
    if should_propose:
        next_sched = clock

    has_to = s.has_timeout(author, pm.active_round)
    deadline = pm.round_start + pm.round_duration
    past_deadline = clock >= deadline
    should_create_timeout = (not has_to) and past_deadline
    should_broadcast = should_broadcast or should_create_timeout
    if (not has_to) and not past_deadline:
        next_sched = min(next_sched, deadline)
    period = (p.lam_fp * pm.round_duration) >> 16
    qad = latest_query_all + period
    should_query_all = has_to and clock >= qad
    if should_query_all:
        qad = clock + period
    if has_to:
        next_sched = min(next_sched, qad)
    return PacemakerActions(
        should_propose, hqc_r, hqc_t, should_create_timeout, pm.active_round,
        send_leader, should_broadcast, should_query_all, next_sched)


def proposed_block_valid(pm: Pacemaker, s: E.Store):
    return (pm.active_epoch == s.epoch_id and pm.active_round == s.current_round
            and pm.active_leader >= 0 and s.proposed_var >= 0)


@dataclasses.dataclass
class NodeUpdateActions:
    next_sched: int
    send_mask: List[bool]
    should_query_all: bool
    ho_switched: bool = False
    ho_epoch: int = -1
    ho_pack: object = None  # Payload | None: old-epoch response pack


def update_node(p, s: E.Store, pm: Pacemaker, nx: NodeExtra, cx: Context,
                weights, author, clock, dur_table):
    n = p.n_nodes
    pa = update_pacemaker(p, pm, s, weights, author, s.epoch_id,
                          nx.latest_query_all, clock, dur_table)
    send_mask = [(i == pa.send_leader and pa.send_leader >= 0) for i in range(n)]
    if pa.should_create_timeout:
        s.create_timeout(weights, author, pa.timeout_round)
        nx.latest_voted_round = max(nx.latest_voted_round, pa.timeout_round)
    if pa.should_propose:
        s.propose_block(weights, author, pa.propose_prev_round,
                        pa.propose_prev_tag, clock, cx.next_cmd_index)
        cx.next_cmd_index += 1

    has_prop = proposed_block_valid(pm, s)
    bvar = max(s.proposed_var, 0)
    block_round = s.current_round
    proposer = s.blk_author[s._slot(block_round)][bvar]
    prev_r = s.previous_round(block_round, bvar)
    may_vote = (has_prop and block_round > nx.latest_voted_round
                and prev_r >= nx.locked_round)
    if may_vote:
        second_prev = s.second_previous_round(block_round, bvar)
        nx.latest_voted_round = block_round
        nx.locked_round = max(nx.locked_round, second_prev)
        voted = s.create_vote(weights, author, block_round, bvar)
        if voted:
            send_mask = [i == proposer for i in range(n)]

    qc_created = s.check_new_qc(weights, author)
    broadcast = pa.should_broadcast or qc_created
    next_sched = clock if qc_created else pa.next_sched

    ho_switched, ho_epoch, ho_pack = process_commits(p, s, nx, cx, weights,
                                                     author)

    nx2, tr_query_all, tr_next = update_tracker(p, nx, s, clock)
    query_all = pa.should_query_all or tr_query_all
    next_sched = min(next_sched, tr_next)
    if query_all:
        nx.latest_query_all = clock
    if broadcast:
        send_mask = [m or (i != author) for i, m in enumerate(send_mask)]
    return NodeUpdateActions(next_sched, send_mask, query_all,
                             ho_switched, ho_epoch, ho_pack)


def process_commits(p, s: E.Store, nx: NodeExtra, cx: Context, weights,
                    author=0):
    """Returns (ho_switched, ho_epoch, ho_pack): the cross-epoch handoff
    capture — the old store's response pack built post-update, pre-switch
    (mirrors core/node.py process_commits)."""
    commits = s.committed_states_after(nx.tracker_hcr)
    H = p.commit_log
    switch = False
    sw_e = sw_d = 0
    sw_t = 0
    for (r, d, t) in commits:
        if switch or d <= cx.last_depth:
            continue
        pos = cx.commit_count % H
        cx.log_round[pos] = r
        cx.log_depth[pos] = d
        cx.log_tag[pos] = t
        cx.commit_count += 1
        cx.skipped_commits += d - cx.last_depth - 1
        cx.last_depth = d
        cx.last_tag = t
        new_epoch = d // p.commands_per_epoch
        if new_epoch > s.epoch_id:
            switch = True
            sw_e, sw_d, sw_t = new_epoch, d, t
    ho_epoch = s.epoch_id
    ho_pack = None
    if p.epoch_handoff and switch:
        ho_pack = handle_request(p, s, author, None)
    if switch:
        fresh = E.Store(p)
        fresh.epoch_id = sw_e
        fresh.initial_tag = E.epoch_initial_tag(sw_e)
        fresh.initial_state_depth = sw_d
        fresh.initial_state_tag = sw_t
        s.__dict__.update(fresh.__dict__)
        nx.latest_voted_round = 0
        nx.locked_round = 0
    return switch, ho_epoch, ho_pack


def update_tracker(p, nx: NodeExtra, s: E.Store, clock):
    epoch_adv = s.epoch_id > nx.tracker_epoch
    commit_adv = s.hcr > nx.tracker_hcr
    bump = epoch_adv or commit_adv
    nx.tracker_epoch = max(nx.tracker_epoch, s.epoch_id)
    if bump:
        nx.tracker_hcr = s.hcr
        nx.tracker_commit_time = clock
    deadline = max(nx.tracker_commit_time, nx.latest_query_all) \
        + p.target_commit_interval
    should_query_all = clock >= deadline
    if should_query_all:
        deadline = clock + p.target_commit_interval
    return nx, should_query_all, deadline


# -- data sync ---------------------------------------------------------------


def qc_msg_at(s: E.Store, r, var, valid):
    sl = s._slot(r)
    blk_var = s.qc_blk_var[sl][var]
    return E.QcMsg(
        valid=bool(valid), epoch=s.epoch_id, round=s.qc_round[sl][var],
        blk_tag=s.blk_tag[sl][blk_var], state_depth=s.qc_state_depth[sl][var],
        state_tag=s.qc_state_tag[sl][var],
        commit_valid=s.qc_commit_valid[sl][var],
        commit_depth=s.qc_commit_depth[sl][var],
        commit_tag=s.qc_commit_tag[sl][var],
        votes_lo=s.qc_votes_lo[sl][var], votes_hi=s.qc_votes_hi[sl][var],
        author=s.qc_author[sl][var], tag=s.qc_tag[sl][var],
    )


def blk_msg_at(s: E.Store, r, var, valid):
    sl = s._slot(r)
    return E.BlockMsg(
        valid=bool(valid), round=s.blk_round[sl][var], author=s.blk_author[sl][var],
        prev_round=s.blk_prev_round[sl][var], prev_tag=s.blk_prev_tag[sl][var],
        time=s.blk_time[sl][var], cmd_proposer=s.blk_cmd_proposer[sl][var],
        cmd_index=s.blk_cmd_index[sl][var], tag=s.blk_tag[sl][var],
    )


def own_vote_msg(p, s: E.Store, author):
    a = min(max(author, 0), p.n_nodes - 1)
    bvar = s.vt_blk_var[a]
    sl = s._slot(s.current_round)
    return E.VoteMsg(
        valid=s.vt_valid[a], epoch=s.epoch_id, round=s.current_round,
        blk_tag=s.blk_tag[sl][bvar], state_depth=s.vt_state_depth[a],
        state_tag=s.vt_state_tag[a], commit_valid=s.vt_commit_valid[a],
        commit_depth=s.vt_commit_depth[a], commit_tag=s.vt_commit_tag[a], author=a,
    )


def create_notification(p, s: E.Store, author) -> E.Payload:
    pay = E.Payload.empty(p.n_nodes, p.chain_k)
    pay.epoch = s.epoch_id
    pay.hcc = qc_msg_at(s, s.hcc_round, s.hcc_var, s.hcc_valid)
    pay.hqc = qc_msg_at(s, s.hqc_round, s.hqc_var, s.hqc_round > 0)
    sl = s._slot(s.current_round)
    prop_var = max(s.proposed_var, 0)
    prop_valid = s.proposed_var >= 0 and s.blk_author[sl][prop_var] == author
    pay.prop_blk = blk_msg_at(s, s.current_round, prop_var, prop_valid)
    pay.vote = own_vote_msg(p, s, author)
    pay.tc_to = E.TimeoutsMsg(s.htc_round, list(s.tc_valid), list(s.tc_hcbr))
    pay.cur_to = E.TimeoutsMsg(s.current_round, list(s.to_valid), list(s.to_hcbr))
    return pay


def create_request(p, s: E.Store) -> E.Payload:
    pay = E.Payload.empty(p.n_nodes, p.chain_k)
    pay.epoch = s.epoch_id
    pay.req_hqc_round = s.hqc_round
    pay.req_hcr = s.hcr
    return pay


def _insert_timeout_batch(p, s: E.Store, weights, to_msg: E.TimeoutsMsg, rec_epoch):
    for a in range(p.n_nodes):
        if to_msg.valid[a]:
            s.insert_timeout(weights, rec_epoch, to_msg.round, to_msg.hcbr[a], a)


def handle_notification(p, s: E.Store, weights, pay: E.Payload):
    should_sync = pay.epoch > s.epoch_id
    if pay.hcc.valid:
        s.insert_qc(weights, pay.hcc)
        should_sync = should_sync or (
            pay.hcc.epoch > s.epoch_id
            or (pay.hcc.epoch == s.epoch_id and pay.hcc.round > s.hcr + 2))
    if pay.hqc.valid:
        s.insert_qc(weights, pay.hqc)
        should_sync = should_sync or (
            pay.hqc.epoch > s.epoch_id
            or (pay.hqc.epoch == s.epoch_id and pay.hqc.round > s.hqc_round))
    if pay.prop_blk.valid:
        s.insert_block(weights, pay.prop_blk, pay.epoch)
    _insert_timeout_batch(p, s, weights, pay.tc_to, pay.epoch)
    _insert_timeout_batch(p, s, weights, pay.cur_to, pay.epoch)
    if pay.vote.valid:
        s.insert_vote(weights, pay.vote)
    return should_sync


def handle_request(p, s: E.Store, author, req: E.Payload) -> E.Payload:
    resp = create_notification(p, s, author)
    hops = s.qc_walk_back(s.hqc_round > 0, s.hqc_round, s.hqc_var, p.chain_k)
    hops = list(reversed(hops))
    resp.chain_blk = []
    resp.chain_qc = []
    for (valid, r, v, _) in hops:
        bvar = s.qc_blk_var[s._slot(r)][v]
        resp.chain_blk.append(blk_msg_at(s, r, bvar, valid))
        resp.chain_qc.append(qc_msg_at(s, r, v, valid))
    hcc_bvar = s.qc_blk_var[s._slot(s.hcc_round)][s.hcc_var]
    resp.hcc_blk = blk_msg_at(s, s.hcc_round, hcc_bvar, s.hcc_valid)
    resp.vote = dataclasses.replace(resp.vote, valid=False)
    return resp


def handle_response(p, s: E.Store, nx: NodeExtra, cx: Context, weights,
                    pay: E.Payload):
    gap_jump = pay.hqc.valid and (
        pay.epoch > s.epoch_id
        or pay.hqc.round > s.hqc_round + (p.window - p.chain_k))
    chain_has_base = pay.chain_qc[0].valid
    do_jump = gap_jump and chain_has_base
    if do_jump:
        base_qc = pay.chain_qc[0]
        fresh = E.Store(p)
        fresh.epoch_id = pay.epoch
        fresh.initial_round = base_qc.round
        fresh.initial_tag = base_qc.tag
        fresh.initial_state_depth = base_qc.state_depth
        fresh.initial_state_tag = base_qc.state_tag
        fresh.current_round = base_qc.round + 1
        fresh.hqc_round = base_qc.round
        fresh.htc_round = base_qc.round
        fresh.hcr = base_qc.round
        fresh.anchored = True
        s.__dict__.update(fresh.__dict__)
        nx.latest_voted_round = 0
        nx.locked_round = 0
        if (pay.hcc.valid and pay.hcc.commit_valid
                and pay.hcc.commit_depth > cx.last_depth):
            cx.skipped_commits += pay.hcc.commit_depth - cx.last_depth
            cx.last_depth = pay.hcc.commit_depth
            cx.last_tag = pay.hcc.commit_tag
        cx.sync_jumps += 1
    for i in range(p.chain_k):
        if do_jump and i == 0:
            continue
        if pay.chain_blk[i].valid:
            s.insert_block(weights, pay.chain_blk[i], pay.epoch)
        if pay.chain_qc[i].valid:
            s.insert_qc(weights, pay.chain_qc[i])
    if pay.hcc_blk.valid:
        s.insert_block(weights, pay.hcc_blk, pay.epoch)
    if pay.hcc.valid:
        s.insert_qc(weights, pay.hcc)
    _insert_timeout_batch(p, s, weights, pay.tc_to, pay.epoch)
    _insert_timeout_batch(p, s, weights, pay.cur_to, pay.epoch)
    if pay.prop_blk.valid:
        s.insert_block(weights, pay.prop_blk, pay.epoch)


# -- the event loop ----------------------------------------------------------


@dataclasses.dataclass
class Message:
    valid: bool
    time: int
    kind: int
    stamp: int
    sender: int
    receiver: int
    payload: E.Payload


class OracleSim:
    """Mirror of sim/simulator.py::step over plain Python state.

    ``attack`` mirrors the adversary plane (adversary/): an
    ``AttackProgram`` (or its dict form, or a pre-lowered
    ``plane.HostPlane``) whose windowed behaviors, per-link delays, and
    partition cuts are replayed per event through the host decode twin —
    the bit-parity reference for every adversarial scenario."""

    def __init__(self, p: SimParams, seed: int, weights=None,
                 byz_equivocate=None, byz_silent=None, byz_forge_qc=None,
                 attack=None):
        self.p = p
        self.seed = seed & E.M32
        n = p.n_nodes
        self.delay_table = p.delay_table()
        self.dur_table = p.duration_table()
        self.weights = list(weights) if weights is not None else [1] * n
        self.byz_equivocate = list(byz_equivocate) if byz_equivocate is not None \
            else [False] * n
        self.byz_silent = list(byz_silent) if byz_silent is not None else [False] * n
        self.byz_forge_qc = list(byz_forge_qc) if byz_forge_qc is not None \
            else [False] * n
        if attack is None:
            self.adv = None
        else:
            from ..adversary import dsl as adsl
            from ..adversary import plane as aplane

            if isinstance(attack, aplane.HostPlane):
                self.adv = attack
            else:
                if isinstance(attack, dict):
                    attack = adsl.AttackProgram.from_dict(attack)
                self.adv = attack.host_plane(p)
        self.stores = [E.Store(p) for _ in range(n)]
        self.pms = [Pacemaker() for _ in range(n)]
        self.nxs = [NodeExtra() for _ in range(n)]
        self.ctxs = [Context(p) for _ in range(n)]
        self.queue: List[Message] = [
            Message(False, 0, 0, 0, 0, 0, E.Payload.empty(n, p.chain_k))
            for _ in range(p.queue_cap)
        ]
        self.startup = [
            int(self.delay_table[(E.rng_u32(self.seed, c) >> (32 - TABLE_BITS))]) + 1
            for c in range(n)
        ]
        self.timer_time = list(self.startup)
        self.timer_stamp = list(range(n))
        # Cross-epoch handoff ring (mirrors SimState.ho_pay / ho_epoch:
        # [N, E] packs, slot = epoch % handoff_epochs).
        E_ho = p.handoff_epochs
        self.ho_pay: List = [[None] * E_ho for _ in range(n)]
        self.ho_epoch = [[-1] * E_ho for _ in range(n)]
        self.n_handoff_served = 0  # oracle-only diagnostic
        self.clock = 0
        self.stamp_ctr = n
        self.halted = False
        self.n_events = 0
        self.n_msgs_sent = 0
        self.n_msgs_dropped = 0
        self.n_queue_full = 0
        T = p.trace_cap
        self.trace_node = [0] * T
        self.trace_round = [0] * T
        self.trace_time = [0] * T
        self.trace_count = 0
        # Telemetry mirror (telemetry/plane.py): the observables the device
        # metrics plane derives per event, kept as raw host values so
        # tests/test_telemetry.py can pin device counters/histograms against
        # exact tallies and raw latency samples.  (drops/overflow/sync-jump
        # slots mirror existing counters and need no extra bookkeeping.)
        self.tel = dict(
            ev_kind=[0, 0, 0, 0],         # processed events by KIND_*
            queue_hwm=0,                  # post-step total queue occupancy
            node_depth_hwm=[0] * n,       # post-step per-receiver depth
            round_lats=[],                # dwell time at each round switch
            commit_lats=[],               # proposal->commit, global time
            commit_lat_miss=0,            # committed block left the window
            flight=[],                    # (kind, actor, time, round, depth)
        )
        # Consensus-watchdog mirror (telemetry/stream.py WD_SLOTS, serial
        # per-event semantics — the lane engine's stall/queue_sat detectors
        # accumulate at window granularity and may legitimately differ;
        # sync_jump/round_regress/safety_conflict are per-event functions
        # of the shared trajectory and match both engines).  Tracked
        # unconditionally (cheap); digest() zeroes it when p.watchdog is
        # off, mirroring the device's zero-width wd leaf.
        self.wd = dict(stall_ev=0, stall=0, queue_sat=0, sync_jump=0,
                       safety_conflict=0, round_regress=0)

    def _select_event(self):
        p = self.p
        cm = p.queue_cap
        times = [m.time if m.valid else NEVER for m in self.queue] + self.timer_time
        kinds = [m.kind for m in self.queue] + [KIND_TIMER] * p.n_nodes
        stamps = [m.stamp for m in self.queue] + self.timer_stamp
        t_min = min(times)
        c1 = [t == t_min for t in times]
        k_best = max(k for k, c in zip(kinds, c1) if c)
        c2 = [c and k == k_best for c, k in zip(c1, kinds)]
        s_best = min(s for s, c in zip(stamps, c2) if c)
        idx = next(i for i, (c, s) in enumerate(zip(c2, stamps)) if c and s == s_best)
        return idx, t_min, idx >= cm

    def _forged_qc(self, s: E.Store, author: int, pay: E.Payload) -> E.Payload:
        """Mirror of sim/simulator.py::_forged_qc_payload."""
        p = self.p
        pay2 = copy.deepcopy(pay)
        bvar = max(s.proposed_var, 0)
        r = s.current_round
        sl = s._slot(r)
        blk_tag_ = s.blk_tag[sl][bvar]
        own = s.proposed_var >= 0 and s.blk_author[sl][bvar] == author
        exec_ok, st_d, st_t = s.compute_state(r, bvar)
        cs_ok, cs_d, cs_t, _ = s.vote_committed_state(r, bvar)
        lo = (1 << author) & E.M32 if author < 32 else 0
        hi = (1 << (author - 32)) & E.M32 if author >= 32 else 0
        tag = E.fold(E.TAG_QC, s.epoch_id & E.M32, r & E.M32, blk_tag_,
                     st_d & E.M32, st_t, int(cs_ok) & E.M32, cs_d & E.M32,
                     cs_t, lo, hi, author & E.M32)
        pay2.hqc = E.QcMsg(
            valid=bool(own and exec_ok), epoch=s.epoch_id, round=r,
            blk_tag=blk_tag_, state_depth=st_d, state_tag=st_t,
            commit_valid=cs_ok, commit_depth=cs_d, commit_tag=cs_t,
            votes_lo=lo, votes_hi=hi, author=author, tag=tag,
        )
        return pay2

    def _equivocated(self, pay: E.Payload) -> E.Payload:
        b = pay.prop_blk
        pay2 = copy.deepcopy(pay)
        pay2.prop_blk.cmd_index = b.cmd_index + EQUIV_SALT
        pay2.prop_blk.tag = E.fold(
            E.TAG_BLOCK, pay.epoch & E.M32, b.round & E.M32, b.author & E.M32,
            b.prev_round & E.M32, b.prev_tag, b.time & E.M32,
            b.cmd_proposer & E.M32, (b.cmd_index + EQUIV_SALT) & E.M32)
        pay2.vote = dataclasses.replace(pay2.vote, valid=False)
        return pay2

    def step(self):
        p = self.p
        n, cm = p.n_nodes, p.queue_cap
        idx, t_min, is_timer = self._select_event()
        if self.halted or t_min > p.max_clock:
            self.halted = True
            return
        clock = max(self.clock, min(t_min, NEVER - 1))
        if is_timer:
            a = idx - cm
            kind = KIND_TIMER
            sender = 0
            pay_in = E.Payload.empty(n, p.chain_k)
        else:
            msg = self.queue[idx]
            kind = msg.kind
            a = min(max(msg.receiver, 0), n - 1)
            sender = msg.sender
            pay_in = msg.payload
            msg.valid = False

        s, pm, nx, cx = self.stores[a], self.pms[a], self.nxs[a], self.ctxs[a]
        local_clock = clock - self.startup[a]

        is_notify = kind == KIND_NOTIFY and not is_timer
        is_request = kind == KIND_REQUEST and not is_timer
        is_response = kind == KIND_RESPONSE and not is_timer
        do_update = is_timer or is_notify or is_response

        self.tel["ev_kind"][KIND_TIMER if is_timer else kind] += 1
        cc_pre = cx.commit_count  # pre-handler, matching the device's cx_a
        sync_pre = cx.sync_jumps  # pre-handler, for the sync-jump detector

        # Adversary plane decode (mirrors sim/simulator.py): keys are the
        # event time, the PRE-event count, and the handled node's
        # PRE-handler epoch; windowed behaviors OR onto the static masks.
        ev_pre = self.n_events
        ep_pre = s.epoch_id
        if self.adv is not None:
            adv_eq, adv_sil, adv_forge = self.adv.node_masks(
                clock, ev_pre, ep_pre, a)
        else:
            adv_eq = adv_sil = adv_forge = False
        eff_equiv = self.byz_equivocate[a] or adv_eq
        eff_silent = self.byz_silent[a] or adv_sil
        eff_forge = self.byz_forge_qc[a] or adv_forge

        should_sync = False
        if is_notify:
            should_sync = handle_notification(p, s, self.weights, pay_in)
        elif is_response:
            handle_response(p, s, nx, cx, self.weights, pay_in)

        pm_round_before = pm.active_round
        pm_start_before = pm.round_start
        if do_update:
            actions = update_node(p, s, pm, nx, cx, self.weights, a, local_clock,
                                  self.dur_table)
        else:
            actions = NodeUpdateActions(NEVER, [False] * n, False)
        if do_update and pm.active_round > pm_round_before:
            if p.trace_cap > 0:
                pos = self.trace_count % p.trace_cap
                self.trace_node[pos] = a
                self.trace_round[pos] = pm.active_round
                self.trace_time[pos] = clock
            self.trace_count += 1
            # Round-switch latency: local-clock dwell in the round just left
            # (mirrors the device's pm_f.round_start - pm_a.round_start).
            self.tel["round_lats"].append(max(pm.round_start - pm_start_before, 0))
        if do_update and cx.commit_count > cc_pre:
            # Proposal -> commit latency of the newest committed entry,
            # recovered from the block table while the block is in-window;
            # lowest valid variant on ties (mirrors telemetry/plane.py
            # commit_latency exactly).
            pos = (cx.commit_count - 1) % p.commit_log
            r_c = cx.log_round[pos]
            sl = r_c % p.window
            v_c = next((v for v in range(p.variants)
                        if s.blk_valid[sl][v] and s.blk_round[sl][v] == r_c),
                       None)
            if v_c is None:
                self.tel["commit_lat_miss"] += 1
            else:
                author_b = min(max(s.blk_author[sl][v_c], 0), n - 1)
                self.tel["commit_lats"].append(max(
                    clock - (s.blk_time[sl][v_c] + self.startup[author_b]), 0))

        silent = eff_silent
        want_sync_req = is_notify and should_sync and not silent
        want_response = is_request and not silent
        cand0_want = want_sync_req or want_response
        cand0_kind = KIND_RESPONSE if want_response else KIND_REQUEST
        cand0_recv = min(max(sender, 0), n - 1)

        send_mask = [m and i != a and do_update and not silent
                     for i, m in enumerate(actions.send_mask)]
        query_mask = [
            (actions.should_query_all and do_update and not silent and i != a)
            for i in range(n)
        ]

        if p.shuffle_receivers:
            # Mirrors sim/simulator.py: stable sort of per-receiver hash keys.
            base = E.rng_u32(self.seed, self.stamp_ctr & E.M32)
            keys = [E.mix32(base, i + 1) for i in range(n)]
            recv_order = sorted(range(n), key=lambda i: (keys[i], i))
        else:
            recv_order = list(range(n))

        # Payload bank (mirrors simulator.py: computed on the post-update store).
        notif = create_notification(p, s, a)
        if eff_forge:
            notif = self._forged_qc(s, a, notif)
        notif_b = self._equivocated(notif)
        request = create_request(p, s)
        response = handle_request(p, s, a, pay_in)
        if eff_forge:
            # The tensor path builds the response from the (forged) notif.
            response.hqc = copy.deepcopy(notif.hqc)

        # Cross-epoch handoff ring (mirrors sim/simulator.py): capture the
        # pack update_node built from the post-update, pre-switch store;
        # serve any requester whose epoch matches a held pack.
        if p.epoch_handoff:
            E_ho = p.handoff_epochs
            if do_update and actions.ho_switched:
                wslot = max(actions.ho_epoch, 0) % E_ho
                self.ho_pay[a][wslot] = copy.deepcopy(actions.ho_pack)
                self.ho_epoch[a][wslot] = actions.ho_epoch
            rslot = max(pay_in.epoch, 0) % E_ho
            if (is_request and pay_in.epoch == self.ho_epoch[a][rslot]
                    and pay_in.epoch < s.epoch_id):
                response = copy.deepcopy(self.ho_pay[a][rslot])
                self.n_handoff_served += 1

        want = ([cand0_want] + [send_mask[i] for i in recv_order]
                + [query_mask[i] for i in recv_order])
        kinds = [cand0_kind] + [KIND_NOTIFY] * n + [KIND_REQUEST] * n
        recvs = [cand0_recv] + recv_order + recv_order
        upper = [(i * 2 >= n) for i in range(n)]
        pays = [response if want_response else request]
        for i in recv_order:
            pays.append(notif_b if (eff_equiv and upper[i]) else notif)
        pays += [request] * n

        timer_gap = 1 if do_update else 0
        pos = -1
        stamps = []
        for j, w in enumerate(want):
            if w:
                pos += 1
            stamps.append(self.stamp_ctr + pos + (timer_gap if j > 0 else 0))
        total_consumed = sum(want) + timer_gap
        timer_stamp_new = self.stamp_ctr + (1 if cand0_want else 0)

        # Leader of the handled node's post-update pacemaker round: the
        # delay_leader behavior's target (mirrors the device's
        # config.leader_of_round(st.weights, pm_f.active_round)).
        adv_leader = (E.leader_of_round(self.weights, pm.active_round)
                      if self.adv is not None else -1)

        free_slots = [i for i, m in enumerate(self.queue) if not m.valid]
        rank = 0
        for j, w in enumerate(want):
            if not w:
                continue
            u_delay = E.rng_u32(self.seed, stamps[j] & E.M32)
            u_drop = E.mix32(u_delay, 0x632BE59B)
            delay = int(self.delay_table[u_delay >> (32 - TABLE_BITS)])
            dropped = u_drop < p.drop_u32
            if self.adv is not None:
                # Network plane: per-link + windowed delay extras ride on
                # the drawn latency; partition-crossing sends before heal
                # are cut (counted with the rng drops, once per message).
                delay += (self.adv.link_extra(a, recvs[j])
                          + self.adv.delay_extra(clock, ev_pre, ep_pre,
                                                 recvs[j], adv_leader))
                dropped = dropped or self.adv.cut(a, recvs[j], clock)
            if dropped:
                self.n_msgs_dropped += 1
                continue
            if rank >= len(free_slots):
                self.n_queue_full += 1
                rank += 1
                continue
            slot = free_slots[rank]
            rank += 1
            self.queue[slot] = Message(
                True, clock + delay, kinds[j], stamps[j], a, recvs[j],
                copy.deepcopy(pays[j]))
            self.n_msgs_sent += 1

        if do_update:
            next_g = NEVER if actions.next_sched >= NEVER else \
                min(actions.next_sched + self.startup[a], NEVER)
            self.timer_time[a] = max(next_g, clock + 1)
            self.timer_stamp[a] = timer_stamp_new

        # Telemetry: post-step queue pressure + flight-recorder entry
        # (mirrors the device's post-write depth_n/qtot and flight row).
        depths = [0] * n
        for mm in self.queue:
            if mm.valid:
                depths[min(max(mm.receiver, 0), n - 1)] += 1
        qtot = sum(depths)
        tel = self.tel
        tel["queue_hwm"] = max(tel["queue_hwm"], qtot)
        tel["node_depth_hwm"] = [
            max(h, d) for h, d in zip(tel["node_depth_hwm"], depths)]
        tel["flight"].append(dict(
            kind=KIND_TIMER if is_timer else kind, actor=a, time=clock,
            round=s.current_round, depth=qtot))

        # Consensus-watchdog mirror (device: sim/simulator.py's watchdog
        # block).  Every detector is a per-event function of the same
        # pre/post values the device compares, so counts pin bit-exactly.
        wd = self.wd
        switched = do_update and pm.active_round > pm_round_before
        stall_ev0 = wd["stall_ev"]
        wd["stall_ev"] = 0 if switched else stall_ev0 + 1
        T = self.p.watchdog_stall_events
        if stall_ev0 < T <= wd["stall_ev"]:
            wd["stall"] += 1
        if qtot >= self.p.queue_cap:
            wd["queue_sat"] += 1
        wd["sync_jump"] += cx.sync_jumps - sync_pre
        if cx.commit_count > cc_pre:
            H = p.commit_log
            pos = (cx.commit_count - 1) % H
            d_new, t_new = cx.log_depth[pos], cx.log_tag[pos]
            if cx.commit_count >= 2:
                pos2 = (cx.commit_count - 2) % H
                same_epoch = (d_new // p.commands_per_epoch
                              == cx.log_depth[pos2] // p.commands_per_epoch)
                if same_epoch and cx.log_round[pos] <= cx.log_round[pos2]:
                    wd["round_regress"] += 1
            conflict = any(
                cb.log_depth[j] == d_new and cb.log_tag[j] != t_new
                for b, cb in enumerate(self.ctxs) if b != a
                for j in range(min(cb.commit_count, H)))
            if conflict:
                wd["safety_conflict"] += 1

        self.clock = clock
        self.stamp_ctr += total_consumed
        self.n_events += 1

    def run(self, max_events: int = 100000):
        for _ in range(max_events):
            if self.halted:
                break
            self.step()
        return self

    def digest(self) -> dict:
        """This instance's fleet-health digest, named per DIGEST_SLOTS
        (telemetry/stream.py) — the host mirror of the device's in-graph
        ``compute_digest`` on a one-instance state.  Fold per-instance
        digests (plus ``pad_digest()`` rows for padding) with
        ``stream.fold_digests`` to pin a whole padded fleet's polled
        vector exactly.  Watchdog slots read 0 when ``p.watchdog`` is
        off, mirroring the device's zero-width wd leaf."""
        from ..telemetry import stream as tstream

        d = dict(
            halted=int(self.halted),
            events=self.n_events,
            commits=sum(cx.commit_count for cx in self.ctxs),
            drops=self.n_msgs_dropped,
            overflow=self.n_queue_full,
            queue_depth_max=sum(1 for m in self.queue if m.valid),
            committed_round_min=min(s.hcr for s in self.stores),
            committed_round_max=max(s.hcr for s in self.stores),
        )
        for name in tstream.WD_DETECTORS:
            d["wd_" + name] = self.wd[name] if self.p.watchdog else 0
        return d

    def committed_chain(self, node):
        cx = self.ctxs[node]
        H = self.p.commit_log
        out = []
        for i in range(max(cx.commit_count - H, 0), cx.commit_count):
            pos = i % H
            out.append((cx.log_depth[pos], cx.log_tag[pos]))
        return out
