"""Simulator CLI, mirroring the reference binary
(/root/reference/librabft-v2/src/main.rs): one (or many) LibraBFTv2
simulations with configurable network/protocol parameters.

    python -m librabft_simulator_tpu.main --nodes 3 --max_clock 1000
    python -m librabft_simulator_tpu.main --instances 10000 --nodes 4 \
        --delay uniform --output_data_files /tmp/out

Beyond the reference CLI, ``--instances`` runs a whole batched fleet (the TPU
point of the rebuild) and ``--commit_chain 2`` switches to the two-chain
HotStuff-style rule (BASELINE config #5).
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys

import jax
import numpy as np

from .core.types import SimParams
from .sim import byzantine as B
from .sim import simulator as S


def build_parser():
    ap = argparse.ArgumentParser(
        prog="librabft_simulator_tpu",
        description="A monte-carlo simulation of the LibraBFT consensus protocol "
                    "(TPU-native batched rebuild)")
    ap.add_argument("--max_clock", type=int, default=1000,
                    help="Time at which to stop the simulation")
    ap.add_argument("--mean", type=float, default=10.0,
                    help="Mean of the network delay distribution")
    ap.add_argument("--variance", type=float, default=4.0,
                    help="Variance of the network delay distribution")
    ap.add_argument("--seed", type=int, default=None,
                    help="Seed for the randomness in the simulation")
    ap.add_argument("--nodes", type=int, default=3, help="Number of nodes")
    ap.add_argument("--commands_per_epoch", type=int, default=30000,
                    help="Commands per epoch (epoch switch trigger)")
    ap.add_argument("--target_commit_interval", type=int, default=100000)
    ap.add_argument("--delta", type=int, default=20,
                    help="Base duration of rounds")
    ap.add_argument("--gamma", type=float, default=2.0,
                    help="Exponent in round duration delta * n^gamma")
    ap.add_argument("--lambda", dest="lam", type=float, default=0.5,
                    help="Query-all period as a fraction of round duration")
    ap.add_argument("--output_data_files", default=None,
                    help="Directory for round-switch CSV + message counts")
    # TPU-rebuild extensions.
    ap.add_argument("--instances", type=int, default=1,
                    help="Number of independent simulations run as one batch")
    ap.add_argument("--delay", default="lognormal",
                    choices=["lognormal", "uniform", "pareto", "constant"])
    ap.add_argument("--drop_prob", type=float, default=0.0)
    ap.add_argument("--commit_chain", type=int, default=3,
                    help="3 = LibraBFTv2 3-chain, 2 = HotStuff-style 2-chain")
    ap.add_argument("--byzantine_f", type=int, default=0,
                    help="Number of faulty authors (0..n/3)")
    # Choices come from THE schedule registry (sim/byzantine.SCHEDULES) so
    # a newly registered schedule can never silently vanish from the CLI
    # (the drift this replaces: the flag offered 2 of the 4 registered
    # kinds).  "honest" is valid and means f faulty authors doing nothing.
    ap.add_argument("--byzantine_kind", default="equivocate",
                    choices=list(B.SCHEDULES))
    ap.add_argument("--json", action="store_true", help="JSON summary to stdout")
    ap.add_argument("--platform", default=None, choices=["cpu", "tpu"],
                    help="force a JAX backend (some TPU plugins ignore "
                         "JAX_PLATFORMS; this flag always wins)")
    ap.add_argument("--no_compile_cache", action="store_true",
                    help="disable the persistent XLA compilation cache")
    return ap


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    if not args.no_compile_cache:
        # The jitted step is a large graph (~minutes of XLA time per new
        # static config); cache compilations across runs — in the one
        # shared cache (utils/cache.py, LIBRABFT_COMPILE_CACHE).
        from .utils.cache import setup_compile_cache

        setup_compile_cache()
    seed = args.seed if args.seed is not None else random.getrandbits(32)
    print(f"seed: {seed}", file=sys.stderr)
    trace = 4096 if args.output_data_files else 0
    p = SimParams(
        n_nodes=args.nodes,
        max_clock=args.max_clock,
        delay_kind=args.delay,
        delay_mean=args.mean,
        delay_variance=args.variance,
        drop_prob=args.drop_prob,
        commands_per_epoch=args.commands_per_epoch,
        target_commit_interval=args.target_commit_interval,
        delta=args.delta,
        gamma=args.gamma,
        lam=args.lam,
        commit_chain=args.commit_chain,
        # In-flight messages scale ~n^2 (each update may broadcast to n-1
        # peers); 16n keeps 16-64-node fleets live (smaller caps starve them).
        queue_cap=max(32, 16 * args.nodes),
        trace_cap=trace,
    )
    seeds = (np.uint32(seed) + np.arange(args.instances, dtype=np.uint32))
    from .telemetry import ledger as tledger

    with tledger.get().span(tledger.RUN, what="main_cli") as sp:
        if args.byzantine_f > 0:
            st = B.init_fault_batch(p, seeds, args.byzantine_f,
                                    args.byzantine_kind)
        else:
            st = S.init_batch(p, seeds)
        st = S.run_to_completion(p, st, batched=True)
    elapsed = sp.dur_s

    cc = np.asarray(jax.device_get(st.ctx.commit_count))
    print(f"Commands executed per node: {cc.tolist() if args.instances == 1 else cc.mean(axis=0).tolist()}",
          file=sys.stderr)
    summary = {
        "seed": int(seed),
        "instances": args.instances,
        "nodes": args.nodes,
        "elapsed_s": round(elapsed, 3),
        "mean_commits_per_node": float(cc.mean()),
        "total_events": int(np.asarray(jax.device_get(st.n_events)).sum()),
        "msgs_sent": int(np.asarray(jax.device_get(st.n_msgs_sent)).sum()),
        "msgs_dropped": int(np.asarray(jax.device_get(st.n_msgs_dropped)).sum()),
    }
    if args.byzantine_f > 0:
        honest = np.arange(p.n_nodes) >= args.byzantine_f
        summary["safe_fraction"] = float(B.check_safety(st, honest).mean())
    if args.output_data_files:
        from .analysis.data_writer import DataWriter

        DataWriter(p, args.output_data_files).write(st, instance=0)
        print(f"wrote data files to {args.output_data_files}", file=sys.stderr)
    if args.json:
        print(json.dumps(summary))
    else:
        for k, v in summary.items():
            print(f"{k}: {v}", file=sys.stderr)
    return summary


if __name__ == "__main__":
    main()
