"""Device-mesh construction and SimState sharding.

Scaling redesign of the reference's single-process simulator loop
(/root/reference/bft-lib/src/simulator.rs:380): instances are embarrassingly
parallel, so the fleet scales across chips by sharding the leading instance
(batch) dimension of the :class:`~librabft_simulator_tpu.core.types.SimState`
pytree over a ``jax.sharding.Mesh`` ('dp' axis).  Within an instance, per-node
aggregations (quorum vote counts) can additionally ride a model-parallel 'mp'
axis via ``shard_map`` + ``psum`` — see :mod:`.sharded`.

XLA inserts all collectives; nothing here issues explicit sends.  On real
hardware the dp axis should map to ICI-adjacent devices (default device order
does this on TPU slices).
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_dp: int | None = None, n_mp: int = 1, devices=None) -> Mesh:
    """A ('dp', 'mp') mesh over the available devices.

    Raises a clear :class:`ValueError` when the requested shape doesn't fit
    the visible devices (a silent ``reshape`` of a short device array would
    otherwise surface as an opaque numpy error deep in mesh construction)."""
    if devices is None:
        devices = jax.devices()
    if n_mp < 1:
        raise ValueError(f"n_mp must be >= 1, got {n_mp}")
    if n_dp is None:
        n_dp = len(devices) // n_mp
    if n_dp < 1:
        raise ValueError(
            f"n_dp must be >= 1, got {n_dp} ({len(devices)} devices visible "
            f"for n_mp={n_mp})")
    if n_dp * n_mp > len(devices):
        raise ValueError(
            f"mesh shape dp={n_dp} x mp={n_mp} needs {n_dp * n_mp} devices "
            f"but only {len(devices)} are visible (on CPU, force virtual "
            "devices with XLA_FLAGS=--xla_force_host_platform_device_count=N)")
    devices = np.asarray(devices[: n_dp * n_mp]).reshape(n_dp, n_mp)
    return Mesh(devices, axis_names=("dp", "mp"))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a [B, ...] instance batch: B split over dp (and mp, when
    mp devices exist, so every chip holds work even in pure-dp runs)."""
    return NamedSharding(mesh, P(("dp", "mp")))


def shard_batch(mesh: Mesh, state):
    """Place every leaf of a batched SimState on the mesh, batch dim split
    over all devices."""
    sh = batch_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sh), state)


def replicate(mesh: Mesh, tree):
    sh = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, sh), tree)
