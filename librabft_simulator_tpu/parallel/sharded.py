"""Sharded fleet runtime: dp over instances, mp over the author dimension.

The round-5 on-chip data showed a single chip's step is kernel-dispatch
bound — events/s is flat in B (PERF_NOTES.md) — and the remote-compile
helper caps on-chip fleets at B=32768 anyway (ROADMAP).  The remaining
throughput lever is therefore MORE DISPATCH ENGINES: shards share no state
(the Chandy–Misra decomposition the lane engine already exploits is
per-instance here), so SPMD over the 'dp' mesh axis is collective-free and
scales with the chip count.  This module is the production runtime for
that:

* **Pipelined dispatch** (:func:`run_sharded`): the compiled chunk returns
  an in-graph ``[D]`` fleet-health digest (telemetry/stream.py — slot 0 is
  the halted count, the rest live observability; one small vector to the
  host per chunk, never the ``[B]`` halt plane), and the host loop is
  double-buffered — chunk *k+1* is enqueued before chunk *k*'s digest is
  polled, so poll latency overlaps device compute.  Buffer donation
  threads the fleet state in place between chunks (at B=100k the ~3.4 GB
  state is never copied).
* **Fleet semantics**: :func:`pad_to_multiple` pads B to the device count
  with pre-halted instances (every engine write is gated on
  ``live = ~halted``, so padding contributes zero events, telemetry, and
  DataWriter traces); :func:`fleet_seeds` folds per-instance PRNG streams
  from one base seed, identically for every dp layout, so a fleet is
  reproducible however it is sharded.
* **shard_map step wrapping** (:func:`make_sharded_run_fn`): the engine's
  chunk scan runs under ``shard_map``, so each shard compiles to its own
  independent while loop over its local batch — per-shard dispatch with no
  partitioner-inserted resharding possible.  ``wrap="jit"`` keeps the
  GSPMD-partitioned form for A/B.
* **mp (author parallelism)**: quorum aggregation (configuration.rs:43
  ``count_votes``) for very large committees (N >> 64) shards the author
  axis over 'mp'.  The aggregation itself lives in ``core/config.py`` and
  is armed inside the step's real quorum checks by
  ``SimParams.mp_authors`` (core/store.py ballot/insert_qc/TC sites);
  :func:`sharded_count_votes` / :func:`sharded_quorum_reached` wrap that
  same implementation in shard_map for standalone use.  Sharding the [N]
  author *state tables* is future work — today n_mp > 1 is for the
  standalone helpers, and ``mp_authors`` runs degenerate-identical at
  n_mp == 1 (tests/test_multichip.py).

XLA inserts all collectives; on real hardware the dp axis should map to
ICI-adjacent devices (default device order does this on TPU slices).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from ..core import config
from ..core import types
from ..core.types import SimParams
from ..sim import simulator as sim_ops
from ..telemetry import ledger as tledger
from ..telemetry import stream as tstream
from ..utils import aot
from ..utils import hashing as H
from ..utils import xops
from . import mesh as mesh_ops

I32 = jnp.int32

#: Filler-seed salt for pad instances (golden-ratio constant): pad seeds
#: are folded from a DIFFERENT base than any caller's fleet_seeds(0, ...),
#: so a padded fleet can never alias a real instance's rng stream.
_PAD_SALT = 0x9E3779B9


def fleet_seeds(base_seed: int, n: int, start: int = 0) -> np.ndarray:
    """Per-instance PRNG streams folded from one base seed.

    Instance *i*'s seed is ``mix32(base_seed, start + i)`` — a pure
    function of the GLOBAL instance index, so the streams are identical for
    every dp layout (1 chip or 64) and a sharded fleet reproduces an
    unsharded one bit-for-bit.  ``start`` lets per-shard hosts derive their
    local slice without materializing the full seed vector."""
    idx = jnp.arange(start, start + n, dtype=jnp.uint32)
    return np.asarray(jax.vmap(
        lambda i: H.mix32(jnp.uint32(base_seed), i))(idx))


def batch_size(state) -> int:
    """Leading (instance) dim of a batched engine state."""
    return int(jax.tree_util.tree_leaves(state)[0].shape[0])


def pad_to_multiple(p: SimParams, state, multiple: int, engine=None):
    """Pad the fleet's batch dim to a multiple of ``multiple`` with
    PRE-HALTED instances; returns ``(padded_state, n_valid)``.

    Padded instances are freshly initialised from salted filler seeds and
    start with ``halted=True``: both engines gate every write on
    ``live = ~halted``, so padding processes no events, sends no messages,
    and leaves its metrics plane, flight ring, and DataWriter trace ring
    all-zero — arithmetic ballast only, masked out of every observable by
    construction (tests/test_multichip.py pins this against the oracle).
    A host (numpy) tree pads on host — numpy concat, filler fetched — so
    checkpoint restores never stage full leaves on the default device."""
    eng = engine if engine is not None else sim_ops
    b = batch_size(state)
    pad = (-b) % max(int(multiple), 1)
    if pad == 0:
        return state, b
    filler = eng.init_batch(p, fleet_seeds(_PAD_SALT, pad, start=b))
    filler = filler.replace(halted=jnp.ones((pad,), jnp.bool_))
    if isinstance(jax.tree_util.tree_leaves(state)[0], np.ndarray):
        filler = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), filler)
        cat = lambda a, x: np.concatenate([a, x.astype(a.dtype)], axis=0)  # noqa: E731
    else:
        cat = lambda a, x: jnp.concatenate([jnp.asarray(a), x], axis=0)  # noqa: E731
    return jax.tree.map(cat, state, filler), b


def unpad(state, n_valid: int):
    """Drop the pad instances appended by :func:`pad_to_multiple`.

    A dp-sharded fleet lands shard-by-shard on HOST (numpy tree): the
    trimmed batch no longer tiles the mesh, so an on-device ``[:n_valid]``
    slice would allgather and hand back every leaf fully replicated — a
    fleet-sized buffer on EVERY chip, exactly what this runtime exists to
    avoid.  The post-run state is a reporting/checkpoint artifact anyway
    (telemetry folds and DataWriter decode fetch to host regardless), and
    ``checkpoint.load_sharded`` re-places a host tree onto a mesh without
    full-leaf staging when the fleet runs again.  Unsharded/host states
    keep the plain slice.

    MULTI-PROCESS meshes (distributed/bootstrap.py) land only the rows
    this process can address — each block is trimmed against its GLOBAL
    batch offset, so a host owning rows ``[s, e)`` gets exactly its valid
    slice and the full fleet never crosses a process boundary (the
    per-host egress contract; ``distributed.egress.local_spans`` names
    the rows).  Single-process fleets see the identical result via the
    same path (all blocks present, globally contiguous)."""
    if batch_size(state) == n_valid:
        return state

    def trim(x):
        shards = getattr(x, "addressable_shards", None)
        fully_local = getattr(getattr(x, "sharding", None),
                              "is_fully_addressable", True)
        if shards is None or (fully_local and len(shards) <= 1):
            return x[:n_valid]
        blocks = {}
        for sh in shards:  # dedup replicated copies by batch span
            start = sh.index[0].start or 0 if sh.index else 0
            if start not in blocks and start < n_valid:
                blocks[start] = np.asarray(sh.data)
        if not blocks:
            # A process can own ONLY padding rows (e.g. b=5 over 4
            # processes pads to 8 and the last process holds [6, 8)):
            # its local valid slice is legitimately empty.
            return np.zeros((0,) + tuple(x.shape[1:]), x.dtype)
        return np.concatenate(
            [blocks[s][:n_valid - s] for s in sorted(blocks)], axis=0)

    return jax.tree.map(trim, state)


def make_sharded_run_fn(p: SimParams, mesh: Mesh, num_steps: int,
                        engine=None, wrap: str = "shard_map"):
    """jit-compiled sharded chunk runner: ``st -> (st, digest)``.

    ``digest`` is the in-graph ``[D]`` int32 fleet-health vector
    (telemetry/stream.py) — slot 0 is ``sum(state.halted)`` reduced across
    the mesh, the rest are events/commits/drops/overflow, live queue
    pressure, min/max committed round, and watchdog trip counts — so the
    host's per-chunk halt poll transfers one small vector instead of the
    full ``[B]`` bool plane, and live fleet visibility rides the sync the
    host already pays for.

    ``wrap="shard_map"`` (default): the engine's chunk scan
    (``engine.make_scan_fn``) is staged under ``shard_map``, so every shard
    compiles to its own independent while loop over its local batch slice —
    per-shard dispatch, and the partitioner can never insert a reshard into
    the hot loop.  ``wrap="jit"``: the GSPMD-partitioned form of the same
    program (shardings pinned via ``with_sharding_constraint``), kept for
    A/B comparison.  Both are bit-identical to the unsharded engines
    (tests/test_multichip.py).  Input buffers are donated: chunk k+1 reuses
    chunk k's memory in place.

    ``num_steps`` counts MACRO-steps: with the serial engine's
    ``SimParams.macro_k`` armed, each shard's chunk retires
    ``num_steps * macro_k`` events per dispatch (sim/simulator.py
    ``macro_step``) — the knob threads through ``engine.make_scan_fn``
    unchanged, and the digest keeps reporting TRUE event counts (its
    event/commit slots are in-state counters accumulated per inner
    iteration, never per-dispatch tallies).

    **Dispatch wrap** (``SimParams.wrap``, resolved via
    ``xops.resolve_params`` — NOT this function's SPMD ``wrap``
    argument): with ``wrap="device"`` the chunk scan is additionally
    wrapped in an in-graph ``lax.while_loop`` that retires up to
    ``SimParams.ring_k`` chunks per dispatched outer program, exits
    early on the all-halted predicate, and streams each retired chunk's
    [D] digest into a device-side ``[ring_k, D]`` int32 ring.  The
    runner's signature becomes ``(st, cap) -> (st, ring, retired)``
    where ``cap`` is a TRACED scalar chunk budget (host clamps it to the
    remaining step budget without a retrace) and ``retired`` counts the
    ring rows actually written.  Chunk bodies are the identical graph,
    so the ring flavor is bit-exact against ``wrap="host"`` per chunk
    (tests/test_multichip.py); requires the shard_map SPMD form (the
    halt predicate is the psum'd digest, replicated across shards, so
    every shard's while loop takes the same trip count).

    The runner is memoized like the engines' ``_compiled_run``: params
    differing only in horizon/drop rate (which ride in SimState) share one
    executable; delay/duration-table variants re-trace, since the tables
    are baked into the scan closure here."""
    eng = engine if engine is not None else sim_ops
    if p.mp_authors and wrap != "shard_map":
        # The quorum psum needs the 'mp' axis BOUND; plain GSPMD jit has
        # no named-axis context, so the trace would die with an opaque
        # "unbound axis name" deep in core/store.py.
        raise ValueError(
            "SimParams.mp_authors requires wrap='shard_map' (the 'mp' "
            "mesh axis must be bound for the quorum psum)")
    if p.mp_authors and mesh.shape.get("mp", 1) > 1:
        # The batch dim shards over BOTH axes here, so mp peers hold
        # DIFFERENT instances: the quorum psum would sum weight tables
        # across unrelated instances and silently livelock the fleet.
        # mp_authors > 1-wide meshes need author-sharded state (future
        # work, see SimParams.mp_authors) — fail loud instead.
        raise ValueError(
            "SimParams.mp_authors with n_mp > 1 is unsupported in the dp "
            "fleet runtime (instances would psum quorum weights across "
            "each other); use n_mp == 1, or the standalone "
            "sharded_count_votes/sharded_quorum_reached helpers for "
            "author-sharded quorums")
    # Normalize the pure-runtime fields (they live in SimState, not the
    # graph) so horizon/drop sweeps share one cache entry; delay/delta/
    # gamma stay in the key — they parameterize the baked tables.  With
    # the scenario plane armed the delay table rides IN STATE (per slot)
    # and the commit rule reads the traced sc_commit selector, so the key
    # gets strictly coarser: delay_* and commit_chain are normalized out
    # exactly as ``structural()`` does, and one sharded executable serves
    # every admitted scenario config — the resident fleet service's
    # no-recompile-on-admission guarantee (serve/service.py).
    key_p = dataclasses.replace(xops.resolve_params(p), max_clock=0,
                                drop_prob=0.0)
    if key_p.scenario:
        key_p = dataclasses.replace(
            key_p, commit_chain=3, **types.DELAY_KEY_DEFAULTS)
    if key_p.wrap == "device" and wrap != "shard_map":
        # The device wrap's while-loop halt predicate is the psum'd
        # digest — uniform across shards only under shard_map's bound
        # mesh axes.  The GSPMD "jit" A/B form stays host-dispatched.
        raise ValueError(
            "SimParams.wrap='device' requires the shard_map SPMD form "
            f"(got wrap={wrap!r}); the in-graph ring loop's halt "
            "predicate needs the mesh axes bound")
    inner = _cached_sharded_run_fn(key_p, mesh, num_steps, eng, wrap)
    eng_name = "sharded/" + ("lane" if eng is not sim_ops else "serial")
    flavor = "ring" if key_p.wrap == "device" else "digest"
    ring_meta = ({"ring_k": key_p.ring_k} if key_p.wrap == "device" else {})
    # AOT executable store (utils/aot.py): consult before tracing — see
    # simulator.make_run_fn.  Unlike the single-chip runners, the delay/
    # duration tables are BAKED into the sharded scan closure, so the
    # store key must carry the full normalized params (key_p), not just
    # structural() — two delay configs are two different executables
    # here.  Mesh layout, SPMD wrap mode, and (for the device dispatch
    # wrap) the ring depth complete the key.
    call = aot.wrap_jit(
        inner, (), key=tledger.params_key(key_p), engine=eng_name,
        flavor=flavor, num_steps=num_steps, wrap=wrap,
        mesh=str(dict(mesh.shape)), **ring_meta)
    # Compile ledger (telemetry/ledger.py): the sharded chunk executable
    # is recorded like the single-chip ones — keyed on the normalized
    # structural params + mesh + shapes, host-side only.
    return tledger.wrap_compile(
        call, key=tledger.params_key(key_p.structural()),
        structural=repr(key_p.structural()),
        engine=eng_name,
        n_nodes=p.n_nodes, num_steps=num_steps, wrap=wrap,
        mesh=str(dict(mesh.shape)), **ring_meta)


@functools.lru_cache(maxsize=None)
def _cached_sharded_run_fn(p: SimParams, mesh: Mesh, num_steps: int,
                           eng, wrap: str):
    axes = tuple(mesh.axis_names)
    if wrap == "shard_map":
        inner = eng.make_scan_fn(p, num_steps, batched=True)
        if p.wrap == "device":
            ring_k = int(p.ring_k)
            halted_slot = tstream.SLOT["halted"]

            def local(st, cap):
                # In-graph chunk retirement: retire up to ``cap`` chunks
                # (cap <= ring_k, the host's remaining-budget clamp) or
                # until the whole fleet halts, streaming each retired
                # chunk's replicated [D] digest into a [ring_k, D] ring.
                # The halt predicate reads the PREVIOUS chunk's psum'd
                # digest, so every shard's loop takes the same trip
                # count; halted=0 initially, so at least one chunk
                # always retires (the host flavor's unconditional first
                # dispatch).  Retiring a chunk on an already-halted
                # fleet would be an exact no-op anyway (live-gated
                # writes), which is what makes the two wraps bit-exact.
                total = (jax.tree_util.tree_leaves(st)[0].shape[0]
                         * mesh.size)
                ring0 = jnp.zeros((ring_k, tstream.DIGEST_WIDTH), I32)

                def cond(carry):
                    _, _, retired, halted = carry
                    return (retired < cap) & (halted < total)

                def body(carry):
                    st, ring, retired, _ = carry
                    st = inner(st)
                    dg = tstream.compute_digest(p, st, axis_names=axes)
                    ring = jax.lax.dynamic_update_slice(
                        ring, dg[None, :], (retired, 0))
                    return st, ring, retired + 1, dg[halted_slot]

                st, ring, retired, _ = jax.lax.while_loop(
                    cond, body, (st, ring0, jnp.int32(0), jnp.int32(0)))
                return st, ring, retired

            f = shard_map(local, mesh=mesh, in_specs=(P(axes), P()),
                          out_specs=(P(axes), P(), P()), check_rep=False)
            return jax.jit(f, donate_argnums=(0,))

        def local(st):
            st = inner(st)
            # Whole-fleet [D] digest: psum/pmax/pmin across the mesh, so
            # every shard returns the same (replicated) vector.
            dg = tstream.compute_digest(p, st, axis_names=axes)
            return st, dg

        f = shard_map(local, mesh=mesh, in_specs=(P(axes),),
                      out_specs=(P(axes), P()), check_rep=False)
        return jax.jit(f, donate_argnums=(0,))
    if wrap != "jit":
        raise ValueError(
            f"unknown wrap mode {wrap!r}; want 'shard_map' or 'jit'")
    run = eng.make_run_fn(p, num_steps, batched=True)
    sh = mesh_ops.batch_sharding(mesh)

    def sharded(st):
        st = jax.lax.with_sharding_constraint(st, sh)
        st = run(st)
        # Global reductions: GSPMD partitions them; the digest value is
        # identical to the shard_map form's.
        return st, tstream.compute_digest(p, st)

    return jax.jit(sharded, donate_argnums=(0,))


def _poll_digest(dg) -> np.ndarray:
    """Blocking host fetch of a chunk's ``[D]`` digest — ONE small vector,
    never a ``[B]`` plane.  The single host-sync point of the fleet loop
    (slot 0 is the halt count; live fleet health rides along for free),
    split out so tests can monkeypatch jax.device_get and assert exactly
    that (tests/test_multichip.py::test_poll_path_fetches_digest_only)."""
    return np.asarray(jax.device_get(dg))


def _poll_ring(ring, retired) -> tuple[np.ndarray, int]:
    """Blocking host fetch of one outer call's ``[ring_k, D]`` digest ring
    plus its retired-chunk count — the device wrap's ONE egress per up-to-
    ring_k retired chunks (vs one :func:`_poll_digest` per chunk on the
    host wrap).  Split out, like ``_poll_digest``, so tests can
    monkeypatch/count exactly the ring fetches."""
    ring_h, n = jax.device_get((ring, retired))
    return np.asarray(ring_h), int(n)


def run_sharded(p: SimParams, mesh: Mesh, state, num_steps: int,
                chunk: int = 256, engine=None, pipeline: bool = True,
                wrap: str = "shard_map", pad: bool = True, stream=None):
    """Pipelined host loop over sharded chunks until the whole fleet halts
    or ``num_steps`` is reached; returns the (unpadded) final state.
    ``num_steps``/``chunk`` count macro-steps — with the serial engine's
    ``SimParams.macro_k`` armed each chunk retires ``chunk * macro_k``
    events per instance (see :func:`make_sharded_run_fn`).

    Double-buffered dispatch: chunk *k+1* is enqueued BEFORE chunk *k*'s
    digest is polled, so the host's one blocking sync per chunk
    (:func:`_poll_digest`, on the LAGGED future only) overlaps device
    compute and the dispatch queues never drain between chunks.  The one
    extra chunk this can run after global halt is a no-op by construction
    (every engine write is gated on ``live = ~halted``), so trajectories
    are bit-identical to the non-pipelined loop — and to the unsharded
    engines.  Donation (make_sharded_run_fn) threads the state in place
    between chunks.  ``pad=True`` pads a B not divisible by the mesh's
    device count with pre-halted instances and strips them on return —
    note that stripping lands a padded fleet's final state on host,
    shard by shard (see :func:`unpad`); an evenly-dividing B returns the
    sharded device state as-is.

    ``stream`` (a telemetry/stream.TimelineRecorder) receives every polled
    digest — the live fleet-health timeline costs ZERO additional host
    syncs because the digest IS the halt poll.  Every dispatched chunk is
    polled exactly once (the final in-flight chunk included), so the
    timeline always ends on the fleet's true final digest.

    **Device dispatch wrap** (``SimParams.wrap="device"``, resolved via
    ``xops.resolve_params``): the loop above moves in-graph — each outer
    call retires up to ``SimParams.ring_k`` chunks (clamped to the
    remaining step budget via a traced ``cap`` scalar, no retrace) and
    the host fetches the ``[ring_k, D]`` digest ring ONCE per outer
    call, so polls-per-retired-chunk drops from 1.0 to <= 1/ring_k on
    non-halting horizons.  The outer loop is sequential (``pipeline`` is
    ignored: the in-graph early exit makes speculative double-buffering
    dispatch up to ring_k no-op chunks).  Every retired chunk's digest
    still reaches ``stream`` in order with true per-chunk counts, and
    trajectories stay bit-identical to ``wrap="host"`` — the chunk
    graph is shared, only the dispatch wrap differs."""
    eng = engine if engine is not None else sim_ops
    n_valid = batch_size(state)
    if pad:
        state, n_valid = pad_to_multiple(p, state, mesh.size, engine=eng)
    b_total = batch_size(state)
    if b_total % mesh.size:
        raise ValueError(
            f"batch {b_total} not divisible over the mesh's {mesh.size} "
            "devices; pass pad=True (default) or pre-pad with "
            "parallel.sharded.pad_to_multiple")
    state = mesh_ops.shard_batch(mesh, sim_ops.dedupe_buffers(state))
    if num_steps <= 0:  # a zero step budget runs nothing (placement only)
        return unpad(state, n_valid)
    run = make_sharded_run_fn(p, mesh, chunk, engine=eng, wrap=wrap)
    if stream is not None:
        stream.set_fleet(total=b_total, n_valid=n_valid)
    halted_slot = tstream.SLOT["halted"]
    rp = xops.resolve_params(p)
    # Serial-engine macro-steps: the recorder's `steps` metadata stays
    # per-instance EVENT-steps (each dispatched step retires k events);
    # the digest's own counters are true in-state values regardless.
    k = sim_ops.macro_k_of(rp) if eng is sim_ops else 1
    # Runtime ledger (telemetry/ledger.py): per-chunk dispatch-enqueue vs
    # blocking-poll spans, from which pipeline_stats measures the
    # double-buffered loop's overlap fraction, dispatch-queue bubbles,
    # and time_to_first_chunk.  Host-side only — the chunk graph and the
    # one-[D]-fetch poll contract are untouched.
    lg = tledger.get()
    rid = lg.new_run("run_sharded", devices=mesh.size, instances=b_total,
                     pipeline=bool(pipeline), chunk_steps=chunk,
                     dispatch_wrap=rp.wrap,
                     **({"ring_k": rp.ring_k} if rp.wrap == "device"
                        else {}))

    if rp.wrap == "device":
        # Ring dispatch: one outer call retires up to ring_k chunks
        # in-graph; the host reads the digest ring once per call.  The
        # POLL span carries ``retired``/``cap`` so ledger.ring_stats can
        # report retired-per-dispatch and polls-per-retired-chunk.
        ring_k = int(rp.ring_k)
        done, ci, oi = 0, 0, 0
        while done < num_steps:
            cap = min(ring_k, -((done - num_steps) // chunk))
            with lg.span(tledger.DISPATCH, run=rid, chunk=ci, outer=oi,
                         cap=cap):
                state, ring, retired = run(state, np.int32(cap))
            with lg.span(tledger.POLL, run=rid, chunk=ci, outer=oi,
                         cap=cap) as sp:
                rows, n = _poll_ring(ring, retired)
                sp.attrs["retired"] = n
            if stream is not None:
                stream.record_ring(
                    rows, n,
                    steps=[(ci + i + 1) * chunk * k for i in range(n)])
            done += n * chunk
            ci += n
            oi += 1
            if int(rows[n - 1][halted_slot]) >= b_total:
                break
        with lg.span(tledger.HOST_MERGE, run=rid):
            return unpad(state, n_valid)

    def poll(dg, done_steps, chunk_i) -> bool:
        with lg.span(tledger.POLL, run=rid, chunk=chunk_i):
            d = _poll_digest(dg)
        if stream is not None:
            stream.record(d, steps=done_steps * k)
        return int(d[halted_slot]) >= b_total

    with lg.span(tledger.DISPATCH, run=rid, chunk=0):
        state, dg = run(state)
    done = chunk
    if pipeline:
        ci = 0
        while done < num_steps:
            lagged = dg
            with lg.span(tledger.DISPATCH, run=rid, chunk=ci + 1):
                state, dg = run(state)  # dispatch k+1, then poll chunk k
            done += chunk
            if poll(lagged, done - chunk, ci):
                ci += 1
                break
            ci += 1
        poll(dg, done, ci)  # the final (possibly in-flight) chunk
    else:
        ci = 0
        while True:
            if poll(dg, done, ci) or done >= num_steps:
                break
            with lg.span(tledger.DISPATCH, run=rid, chunk=ci + 1):
                state, dg = run(state)
            done += chunk
            ci += 1
    with lg.span(tledger.HOST_MERGE, run=rid):
        return unpad(state, n_valid)


# ---------------------------------------------------------------------------
# Author-dim (mp) quorum aggregation.  The aggregation math lives in
# core/config.py (one implementation for single-chip and sharded); these
# wrappers stage it under shard_map with the author axis split over 'mp' —
# the same psum path the step's quorum checks arm via SimParams.mp_authors.
# ---------------------------------------------------------------------------


def sharded_count_votes(mesh: Mesh, weights, author_mask):
    """count_votes (configuration.rs:43) with the author axis sharded over
    'mp': each chip sums its local authors via ``config.count_votes``, whose
    psum rides ICI."""

    def local(w, m):
        return jnp.reshape(
            config.count_votes(w, m, axis_name=config.MP_AXIS), (1,))

    f = shard_map(
        local, mesh=mesh,
        in_specs=(P("mp"), P("mp")),
        out_specs=P(),
    )
    return f(weights, author_mask)[0]


def sharded_quorum_reached(mesh: Mesh, weights, author_mask):
    """Whether the masked authors reach the 2N/3+1 quorum — the exact
    predicate of the step's quorum sites (``config.count_votes`` vs
    ``config.quorum_threshold``), with both reductions mp-psums."""

    def local(w, m):
        got = config.count_votes(w, m, axis_name=config.MP_AXIS)
        thr = config.quorum_threshold(w, axis_name=config.MP_AXIS)
        return jnp.reshape(got >= thr, (1,))

    f = shard_map(local, mesh=mesh, in_specs=(P("mp"), P("mp")), out_specs=P())
    return f(weights, author_mask)[0]
