"""Sharded execution paths: dp over instances, mp over the author dimension.

Two multi-chip strategies (usable together on a ('dp', 'mp') mesh):

* **dp (instance parallelism)** — the default scale-out: the [B, ...] batch is
  split across chips; the jitted vmapped step needs no cross-instance
  communication, so XLA compiles a collective-free SPMD program.

* **mp (author parallelism)** — inside an instance, per-author tables
  (votes, timeouts, weights: the [N] axes) are split over 'mp'; quorum
  aggregation (configuration.rs:43 ``count_votes``) becomes a
  ``psum`` over the mp axis.  This is the pattern for very large committees
  (N ≫ 64) where one chip's HBM or vector lanes shouldn't hold the whole
  author axis.  Exposed as :func:`sharded_count_votes` /
  :func:`sharded_quorum_reached` and exercised by ``dryrun_multichip``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from ..core.types import SimParams
from ..sim import simulator as sim_ops
from . import mesh as mesh_ops


def make_sharded_run_fn(p: SimParams, mesh: Mesh, num_steps: int,
                        engine=None):
    """jit-compiled scan of ``num_steps`` events (serial engine) or windows
    (``engine=sim.parallel_sim``), batch dim sharded over the mesh.
    Input/output shardings are pinned so the compiled program is pure SPMD
    with no resharding — both engines are collective-free over dp."""
    eng = engine if engine is not None else sim_ops
    run = eng.make_run_fn(p, num_steps, batched=True)  # jitted vmapped scan
    sh = mesh_ops.batch_sharding(mesh)

    def sharded(st):
        st = jax.lax.with_sharding_constraint(st, sh)
        return run(st)

    return jax.jit(sharded, donate_argnums=(0,))


def run_sharded(p: SimParams, mesh: Mesh, state, num_steps: int,
                chunk: int = 256, engine=None):
    """Host loop over sharded chunks until all instances halt."""
    import numpy as np

    run = make_sharded_run_fn(p, mesh, chunk, engine=engine)
    state = mesh_ops.shard_batch(mesh, sim_ops.dedupe_buffers(state))
    done_steps = 0
    while done_steps < num_steps:
        state = run(state)
        done_steps += chunk
        if bool(np.all(jax.device_get(state.halted))):
            break
    return state


# ---------------------------------------------------------------------------
# Author-dim (mp) quorum aggregation via psum.
# ---------------------------------------------------------------------------


def sharded_count_votes(mesh: Mesh, weights, author_mask):
    """count_votes (configuration.rs:43) with the author axis sharded over
    'mp': each chip sums its local authors, then a psum over mp rides ICI."""

    def local(w, m):
        partial = jnp.sum(jnp.where(m, w, 0), axis=-1, keepdims=True)
        return jax.lax.psum(partial, axis_name="mp")

    f = shard_map(
        local, mesh=mesh,
        in_specs=(P("mp"), P("mp")),
        out_specs=P(),
    )
    return f(weights, author_mask)[0]


def sharded_quorum_reached(mesh: Mesh, weights, author_mask):
    """Whether the masked authors reach the 2N/3+1 quorum, computed with both
    the mask sum and the total weight as mp-psums."""

    def local(w, m):
        got = jax.lax.psum(jnp.sum(jnp.where(m, w, 0), keepdims=True), "mp")
        total = jax.lax.psum(jnp.sum(w, keepdims=True), "mp")
        return got >= 2 * total // 3 + 1

    f = shard_map(local, mesh=mesh, in_specs=(P("mp"), P("mp")), out_specs=P())
    return f(weights, author_mask)[0]
