"""Crypto for the real-node deployment stack
(/root/reference/crypto/src/lib.rs): Digest, Ed25519 keys, Signature,
SignatureService.

Uses the ``cryptography`` package's Ed25519 (same algorithm as the reference's
ed25519-dalek) and SHA-512 truncated to 32 bytes for digests
(crypto/src/lib.rs:33-58).
"""

from __future__ import annotations

import asyncio
import base64
import dataclasses
import hashlib

from cryptography.hazmat.primitives import serialization
from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey,
    Ed25519PublicKey,
)

DIGEST_SIZE = 32


@dataclasses.dataclass(frozen=True, order=True)
class Digest:
    """32-byte digest (crypto/src/lib.rs:20-31)."""

    data: bytes

    def __post_init__(self):
        assert len(self.data) == DIGEST_SIZE

    def to_vec(self) -> bytes:
        return self.data

    def hex(self) -> str:
        return self.data.hex()

    @classmethod
    def of(cls, *chunks: bytes) -> "Digest":
        h = hashlib.sha512()
        for c in chunks:
            h.update(c)
        return cls(h.digest()[:DIGEST_SIZE])


class CryptoError(Exception):
    pass


@dataclasses.dataclass(frozen=True)
class PublicKey:
    """crypto/src/lib.rs:62-108."""

    data: bytes  # 32 raw bytes

    def to_base64(self) -> str:
        return base64.b64encode(self.data).decode()

    @classmethod
    def from_base64(cls, s: str) -> "PublicKey":
        return cls(base64.b64decode(s))

    def _key(self) -> Ed25519PublicKey:
        return Ed25519PublicKey.from_public_bytes(self.data)


@dataclasses.dataclass(frozen=True)
class SecretKey:
    """crypto/src/lib.rs:110-149 (stores seed||public like dalek's 64-byte)."""

    data: bytes  # 32-byte seed + 32-byte public

    def to_base64(self) -> str:
        return base64.b64encode(self.data).decode()

    @classmethod
    def from_base64(cls, s: str) -> "SecretKey":
        return cls(base64.b64decode(s))

    def _key(self) -> Ed25519PrivateKey:
        return Ed25519PrivateKey.from_private_bytes(self.data[:32])


def generate_keypair() -> tuple[PublicKey, SecretKey]:
    """generate_production_keypair (crypto/src/lib.rs:152-166)."""
    sk = Ed25519PrivateKey.generate()
    pub = sk.public_key().public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw)
    seed = sk.private_bytes(
        serialization.Encoding.Raw, serialization.PrivateFormat.Raw,
        serialization.NoEncryption())
    return PublicKey(pub), SecretKey(seed + pub)


@dataclasses.dataclass(frozen=True)
class Signature:
    """crypto/src/lib.rs:169-211."""

    data: bytes  # 64 bytes

    @classmethod
    def new(cls, digest: Digest, secret: SecretKey) -> "Signature":
        return cls(secret._key().sign(digest.data))

    def verify(self, digest: Digest, public_key: PublicKey) -> None:
        try:
            public_key._key().verify(self.data, digest.data)
        except Exception as e:  # InvalidSignature
            raise CryptoError(f"invalid signature: {e}") from e

    @staticmethod
    def verify_batch(digest: Digest, votes) -> None:
        """votes: iterable of (PublicKey, Signature) (lib.rs:196-211)."""
        for pk, sig in votes:
            sig.verify(digest, pk)


class SignatureService:
    """Async signing service (crypto/src/lib.rs:213-238): requests are
    serialized through a queue so the secret key lives in one task."""

    def __init__(self, secret: SecretKey):
        self._queue: asyncio.Queue = asyncio.Queue(100)
        self._secret = secret
        self._task: asyncio.Task | None = None

    def _ensure_task(self):
        if self._task is None:
            self._task = asyncio.get_event_loop().create_task(self._run())

    async def _run(self):
        while True:
            digest, fut = await self._queue.get()
            if not fut.cancelled():
                fut.set_result(Signature.new(digest, self._secret))

    async def request_signature(self, digest: Digest) -> Signature:
        self._ensure_task()
        fut = asyncio.get_event_loop().create_future()
        await self._queue.put((digest, fut))
        return await fut

    def close(self):
        if self._task is not None:
            self._task.cancel()
            self._task = None
