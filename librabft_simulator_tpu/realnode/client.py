"""Transaction load generator (/root/reference/node/src/client.rs):

    python -m librabft_simulator_tpu.realnode.client --target 127.0.0.1:7101 \
        --size 512 --rate 1000 --duration 10

Sends fixed-size transactions at a steady rate to a node's mempool port.
Sample transactions (every ``--sample-every``-th) start with a 0 byte + an
8-byte counter id, mirroring the reference's benchmark tagging scheme.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
import time

from .network import write_frame


async def run_client(host: str, port: int, size: int, rate: float,
                     duration: float, sample_every: int = 100) -> int:
    reader, writer = await asyncio.open_connection(host, port)
    interval = 1.0 / rate if rate > 0 else 0.0
    sent = 0
    counter = 0
    t_end = time.monotonic() + duration
    next_t = time.monotonic()
    try:
        while time.monotonic() < t_end:
            if sample_every and sent % sample_every == 0:
                counter += 1
                tx = b"\x00" + counter.to_bytes(8, "big") + os.urandom(max(size - 9, 0))
            else:
                tx = b"\x01" + os.urandom(max(size - 1, 0))
            await write_frame(writer, tx)
            sent += 1
            next_t += interval
            delay = next_t - time.monotonic()
            if delay > 0:
                await asyncio.sleep(delay)
    finally:
        writer.close()
    return sent


def main(argv=None):
    ap = argparse.ArgumentParser(prog="client")
    ap.add_argument("--target", required=True, help="host:port of a mempool")
    ap.add_argument("--size", type=int, default=512, help="transaction bytes")
    ap.add_argument("--rate", type=float, default=1000.0, help="tx/s")
    ap.add_argument("--duration", type=float, default=10.0, help="seconds")
    ap.add_argument("--sample-every", type=int, default=100)
    args = ap.parse_args(argv)
    host, port = args.target.rsplit(":", 1)
    sent = asyncio.run(run_client(host, int(port), args.size, args.rate,
                                  args.duration, args.sample_every))
    print(f"sent {sent} transactions", file=sys.stderr)


if __name__ == "__main__":
    main()
