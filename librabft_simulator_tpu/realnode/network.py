"""Asyncio TCP networking for the real-node stack
(/root/reference/network/src/{receiver,simple_sender,reliable_sender}.rs).

Frames are length-delimited (4-byte big-endian length prefix), matching the
reference's ``LengthDelimitedCodec`` default.  One connection task per peer;
``ReliableSender`` retransmits with exponential backoff until an ACK frame
arrives (reliable_sender.rs:120-190).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable, Dict, List, Tuple

log = logging.getLogger(__name__)

Address = Tuple[str, int]
# handler(writer, message) -> None; use writer to send replies/ACKs.
MessageHandler = Callable[["Writer", bytes], Awaitable[None]]


async def write_frame(w: asyncio.StreamWriter, data: bytes) -> None:
    w.write(len(data).to_bytes(4, "big") + data)
    await w.drain()


async def read_frame(r: asyncio.StreamReader) -> bytes:
    header = await r.readexactly(4)
    size = int.from_bytes(header, "big")
    return await r.readexactly(size)


class Writer:
    """Reply-side of a connection handed to MessageHandlers (receiver.rs:18)."""

    def __init__(self, w: asyncio.StreamWriter):
        self._w = w

    async def send(self, data: bytes) -> None:
        await write_frame(self._w, data)


class Receiver:
    """network/src/receiver.rs:31-90: accept connections, one runner each."""

    def __init__(self, address: Address, handler: MessageHandler):
        self.address = address
        self.handler = handler
        self._server: asyncio.AbstractServer | None = None
        self._conns: set[asyncio.StreamWriter] = set()

    async def spawn(self) -> None:
        host, port = self.address
        self._server = await asyncio.start_server(self._runner, host, port)
        log.debug("listening on %s:%s", host, port)

    async def _runner(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        peer = writer.get_extra_info("peername")
        w = Writer(writer)
        self._conns.add(writer)
        try:
            while True:
                msg = await read_frame(reader)
                await self.handler(w, msg)
        except (asyncio.IncompleteReadError, ConnectionResetError,
                asyncio.CancelledError):
            log.debug("connection closed by peer %s", peer)
        finally:
            self._conns.discard(writer)
            writer.close()

    async def close(self) -> None:
        if self._server:
            self._server.close()
            # Drop live connections so handler coroutines blocked in
            # read_frame terminate (3.12 wait_closed waits for them).
            for w in list(self._conns):
                w.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2.0)
            except asyncio.TimeoutError:
                pass


class _Connection:
    """One keep-alive connection task (simple_sender.rs:76-143)."""

    def __init__(self, address: Address):
        self.address = address
        self.queue: asyncio.Queue = asyncio.Queue(1000)
        self.task = asyncio.get_event_loop().create_task(self._run())

    async def _run(self):
        while True:
            data = await self.queue.get()
            try:
                reader, writer = await asyncio.open_connection(*self.address)
            except OSError as e:
                log.debug("failed to connect to %s: %s", self.address, e)
                continue  # best effort: drop this message
            try:
                await write_frame(writer, data)
                while True:
                    data = await self.queue.get()
                    await write_frame(writer, data)
            except (OSError, ConnectionResetError) as e:
                log.debug("connection to %s failed: %s", self.address, e)
            finally:
                writer.close()


class SimpleSender:
    """Best-effort sender (simple_sender.rs:22-75)."""

    def __init__(self):
        self._connections: Dict[Address, _Connection] = {}

    def _conn(self, address: Address) -> _Connection:
        if address not in self._connections:
            self._connections[address] = _Connection(address)
        return self._connections[address]

    async def send(self, address: Address, data: bytes) -> None:
        await self._conn(address).queue.put(data)

    async def broadcast(self, addresses: List[Address], data: bytes) -> None:
        for a in addresses:
            await self.send(a, data)

    def close(self):
        for c in self._connections.values():
            c.task.cancel()
        self._connections.clear()


class _ReliableConnection:
    """Retransmit-until-ACK connection (reliable_sender.rs:100-248)."""

    RETRY_DELAY = 0.2
    MAX_DELAY = 5.0

    def __init__(self, address: Address):
        self.address = address
        self.queue: asyncio.Queue = asyncio.Queue(1000)
        self.task = asyncio.get_event_loop().create_task(self._run())

    async def _run(self):
        delay = self.RETRY_DELAY
        pending: list = []
        while True:
            if not pending:
                pending.append(await self.queue.get())
            data, fut = pending[0]
            if fut.cancelled():
                pending.pop(0)
                continue
            try:
                reader, writer = await asyncio.open_connection(*self.address)
                try:
                    while pending:
                        data, fut = pending[0]
                        if fut.cancelled():
                            pending.pop(0)
                            continue
                        await write_frame(writer, data)
                        ack = await read_frame(reader)
                        if not fut.cancelled():
                            fut.set_result(ack)
                        pending.pop(0)
                        delay = self.RETRY_DELAY
                        # Pick up any further queued messages without closing.
                        while not self.queue.empty():
                            pending.append(self.queue.get_nowait())
                    # Wait for more work on the open socket.
                    item = await self.queue.get()
                    pending.append(item)
                finally:
                    writer.close()
            except (OSError, asyncio.IncompleteReadError, ConnectionResetError):
                await asyncio.sleep(delay)
                delay = min(delay * 2, self.MAX_DELAY)


class ReliableSender:
    """reliable_sender.rs:31-99: send returns a CancelHandler future that
    resolves with the ACK payload."""

    def __init__(self):
        self._connections: Dict[Address, _ReliableConnection] = {}

    def _conn(self, address: Address) -> _ReliableConnection:
        if address not in self._connections:
            self._connections[address] = _ReliableConnection(address)
        return self._connections[address]

    async def send(self, address: Address, data: bytes) -> asyncio.Future:
        fut = asyncio.get_event_loop().create_future()
        await self._conn(address).queue.put((data, fut))
        return fut

    async def broadcast(self, addresses: List[Address], data: bytes):
        return [await self.send(a, data) for a in addresses]

    def close(self):
        for c in self._connections.values():
            c.task.cancel()
        self._connections.clear()
