"""Persistent KV store with notify_read (/root/reference/store/src/lib.rs).

The reference wraps rocksdb behind a command channel.  Here: an append-only
log file + in-memory index (crash-recoverable on reopen) behind an asyncio
queue, with the same three commands — Write, Read, NotifyRead (a read that
blocks until the key exists; store/src/lib.rs:44-57).
"""

from __future__ import annotations

import asyncio
import os
import struct
from collections import defaultdict
from typing import Dict, List, Optional


class Store:
    def __init__(self, path: str):
        self.path = path
        self._index: Dict[bytes, bytes] = {}
        self._obligations: Dict[bytes, List[asyncio.Future]] = defaultdict(list)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._recover()
        self._log = open(path, "ab")
        self._lock = asyncio.Lock()

    def _recover(self):
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            data = f.read()
        off = 0
        while off + 8 <= len(data):
            klen, vlen = struct.unpack_from(">II", data, off)
            off += 8
            if off + klen + vlen > len(data):
                break  # torn tail write
            key = data[off:off + klen]
            off += klen
            value = data[off:off + vlen]
            off += vlen
            self._index[key] = value

    async def write(self, key: bytes, value: bytes) -> None:
        async with self._lock:
            self._log.write(struct.pack(">II", len(key), len(value)) + key + value)
            self._log.flush()
            self._index[key] = value
            for fut in self._obligations.pop(key, []):
                if not fut.cancelled():
                    fut.set_result(value)

    async def read(self, key: bytes) -> Optional[bytes]:
        return self._index.get(key)

    async def notify_read(self, key: bytes) -> bytes:
        if key in self._index:
            return self._index[key]
        fut = asyncio.get_event_loop().create_future()
        self._obligations[key].append(fut)
        return await fut

    def close(self):
        self._log.close()
