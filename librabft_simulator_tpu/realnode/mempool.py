"""Mempool: committee config, batch maker, batch processor
(/root/reference/mempool/src/{config,batch_maker,processor,mempool}.rs).

Clients send raw transaction frames to the mempool's TCP port; the BatchMaker
seals them into batches by size or timeout (batch_maker.rs:58-86); the
Processor hashes each batch, persists it, and exposes sealed batch digests to
the consensus driver as commands to order.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
from typing import Dict, List, Optional, Tuple

from .crypto import Digest, PublicKey
from .network import Receiver, Writer
from .store import Store

Address = Tuple[str, int]


@dataclasses.dataclass
class Parameters:
    """mempool/src/config.rs:10-22."""

    batch_size: int = 500_000
    max_batch_delay: float = 0.2  # seconds (reference: 200 ms)


@dataclasses.dataclass
class Authority:
    name: PublicKey
    stake: int
    address: Address          # consensus port
    mempool_address: Address  # transaction ingress port


class Committee:
    """mempool/src/config.rs:31-77."""

    def __init__(self, info: List[Authority], epoch: int = 0):
        self.authorities: Dict[str, Authority] = {
            a.name.to_base64(): a for a in info
        }
        self.epoch = epoch

    def stake(self, name: PublicKey) -> int:
        a = self.authorities.get(name.to_base64())
        return a.stake if a else 0

    def total_votes(self) -> int:
        return sum(a.stake for a in self.authorities.values())

    def quorum_threshold(self) -> int:
        return 2 * self.total_votes() // 3 + 1

    def validity_threshold(self) -> int:
        return (self.total_votes() + 2) // 3

    def address(self, name: PublicKey) -> Optional[Address]:
        a = self.authorities.get(name.to_base64())
        return a.address if a else None

    def broadcast_addresses(self, myself: PublicKey) -> List[Address]:
        me = myself.to_base64()
        return [a.address for k, a in self.authorities.items() if k != me]

    def names(self) -> List[PublicKey]:
        return [a.name for a in self.authorities.values()]

    def to_json(self) -> str:
        return json.dumps({
            "epoch": self.epoch,
            "authorities": [
                {"name": a.name.to_base64(), "stake": a.stake,
                 "address": list(a.address),
                 "mempool_address": list(a.mempool_address)}
                for a in self.authorities.values()
            ],
        }, indent=2)

    @classmethod
    def from_json(cls, s: str) -> "Committee":
        d = json.loads(s)
        return cls(
            [Authority(PublicKey.from_base64(a["name"]), a["stake"],
                       tuple(a["address"]), tuple(a["mempool_address"]))
             for a in d["authorities"]],
            d.get("epoch", 0),
        )


class BatchMaker:
    """mempool/src/batch_maker.rs:22-100."""

    def __init__(self, params: Parameters, out_queue: asyncio.Queue):
        self.params = params
        self.out = out_queue
        self._batch: List[bytes] = []
        self._size = 0
        self._task = asyncio.get_event_loop().create_task(self._timer_loop())

    async def add_transaction(self, tx: bytes) -> None:
        self._batch.append(tx)
        self._size += len(tx)
        if self._size >= self.params.batch_size:
            await self._seal()

    async def _seal(self) -> None:
        if not self._batch:
            return
        batch, self._batch, self._size = self._batch, [], 0
        payload = b"".join(len(t).to_bytes(4, "big") + t for t in batch)
        await self.out.put(payload)

    async def _timer_loop(self) -> None:
        while True:
            await asyncio.sleep(self.params.max_batch_delay)
            await self._seal()

    def close(self):
        self._task.cancel()


class Processor:
    """mempool/src/processor.rs: hash the sealed batch, persist it, output the
    digest as an orderable command."""

    def __init__(self, store: Store, in_queue: asyncio.Queue,
                 digest_queue: asyncio.Queue):
        self.store = store
        self.inq = in_queue
        self.outq = digest_queue
        self._task = asyncio.get_event_loop().create_task(self._run())

    async def _run(self) -> None:
        while True:
            batch = await self.inq.get()
            digest = Digest.of(batch)
            await self.store.write(digest.to_vec(), batch)
            # Bounded: shed the oldest digest under backlog (the batch itself
            # is already persisted; only the ordering hint is dropped).
            if self.outq.full():
                self.outq.get_nowait()
            self.outq.put_nowait(digest)

    def close(self):
        self._task.cancel()


class Mempool:
    """mempool/src/mempool.rs: TCP ingress -> BatchMaker -> Processor."""

    def __init__(self, address: Address, params: Parameters, store: Store):
        self.digests: asyncio.Queue = asyncio.Queue(10_000)
        self._sealed: asyncio.Queue = asyncio.Queue()
        self.batch_maker = BatchMaker(params, self._sealed)
        self.processor = Processor(store, self._sealed, self.digests)
        self.receiver = Receiver(address, self._handle)

    async def _handle(self, writer: Writer, message: bytes) -> None:
        await self.batch_maker.add_transaction(message)

    async def spawn(self) -> None:
        await self.receiver.spawn()

    async def next_command(self) -> Digest:
        """The consensus driver's CommandFetcher hook."""
        return await self.digests.get()

    def try_next_command(self) -> Optional[Digest]:
        try:
            return self.digests.get_nowait()
        except asyncio.QueueEmpty:
            return None

    async def close(self) -> None:
        self.batch_maker.close()
        self.processor.close()
        await self.receiver.close()
