"""bft-driver: configs, timer and the consensus core driving the LibraBFTv2
state machine over real asyncio networking
(/root/reference/bft-driver/src/{config,timer,consensus,context,core}.rs).

The per-node protocol state machine is the *oracle* engine
(:mod:`librabft_simulator_tpu.oracle`) — the same plain-Python interpreter
whose semantics are parity-tested against the TPU path, here fed by real
sockets and a real clock instead of the discrete-event queue.  Payloads are
JSON frames (the reference uses bincode; the wire format is an implementation
detail behind the MessageHandler boundary).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import time
from typing import Dict, List, Optional, Tuple

from ..core.types import KIND_NOTIFY, KIND_REQUEST, KIND_RESPONSE, SimParams
from ..oracle import engine as E
from ..oracle import sim as O
from .crypto import Digest, PublicKey, SecretKey, Signature, SignatureService
from .mempool import Committee, Mempool, Parameters
from .network import Receiver, ReliableSender, SimpleSender, Writer
from .store import Store

log = logging.getLogger(__name__)

Address = Tuple[str, int]


@dataclasses.dataclass
class NodeParameters:
    """bft-driver/src/config.rs: protocol knobs (+ tensor-path capacities so
    the state machine is identical to the simulated one)."""

    target_commit_interval: int = 5_000_000
    delta: int = 500         # ms: real networks need wider rounds than sim units
    gamma: float = 1.5
    lam: float = 0.5
    sync_retry_delay: int = 1000

    def to_sim_params(self, n_nodes: int) -> SimParams:
        return SimParams(
            n_nodes=n_nodes,
            target_commit_interval=self.target_commit_interval,
            delta=self.delta,
            gamma=self.gamma,
            lam=self.lam,
            window=64,
            # One catch-up can deliver up to window-(C-1) commits in a single
            # update; commit_log must cover that or the ring-read below would
            # drop/duplicate entries.
            commit_log=64,
            queue_cap=max(32, 4 * n_nodes),
            max_clock=2**31 - 2,
        )


def _payload_to_json(pay: E.Payload) -> dict:
    return dataclasses.asdict(pay)


def _payload_from_json(d: dict, n: int, k: int) -> E.Payload:
    pay = E.Payload.empty(n, k)
    pay.epoch = d["epoch"]
    pay.hcc = E.QcMsg(**d["hcc"])
    pay.hqc = E.QcMsg(**d["hqc"])
    pay.hcc_blk = E.BlockMsg(**d["hcc_blk"])
    pay.prop_blk = E.BlockMsg(**d["prop_blk"])
    pay.vote = E.VoteMsg(**d["vote"])
    pay.tc_to = E.TimeoutsMsg(**d["tc_to"])
    pay.cur_to = E.TimeoutsMsg(**d["cur_to"])
    pay.chain_blk = [E.BlockMsg(**b) for b in d["chain_blk"]]
    pay.chain_qc = [E.QcMsg(**q) for q in d["chain_qc"]]
    pay.req_hqc_round = d["req_hqc_round"]
    pay.req_hcr = d["req_hcr"]
    return pay


class Timer:
    """bft-driver/src/timer.rs: a resettable deadline."""

    def __init__(self):
        self._deadline: Optional[float] = None
        self._event = asyncio.Event()

    def schedule(self, deadline_ms: float):
        self._deadline = deadline_ms
        self._event.set()

    async def wait(self, now_ms) -> None:
        while True:
            if self._deadline is None:
                await self._event.wait()
                self._event.clear()
                continue
            delta = (self._deadline - now_ms()) / 1000.0
            if delta <= 0:
                self._deadline = None
                return
            try:
                await asyncio.wait_for(self._event.wait(), timeout=delta)
                self._event.clear()
            except asyncio.TimeoutError:
                pass


class ConsensusCore:
    """bft-driver/src/core.rs: the node main loop.

    Wires: timer -> update_node; network notifications/requests/responses ->
    oracle data-sync handlers -> update_node; update actions -> sends.
    """

    def __init__(self, index: int, committee: Committee, secret: SecretKey,
                 params: NodeParameters, mempool: Optional[Mempool],
                 store: Store, address: Address):
        n = len(committee.authorities)
        self.index = index
        self.committee = committee
        self.params = params
        self.p = params.to_sim_params(n)
        self.sig_service = SignatureService(secret)
        self.mempool = mempool
        self.store = store
        self.address = address
        self.weights = [committee.stake(name) for name in committee.names()]
        self.s = E.Store(self.p)
        self.pm = O.Pacemaker()
        self.nx = O.NodeExtra()
        self.cx = O.Context(self.p)
        self.dur_table = self.p.duration_table()
        self.sender = SimpleSender()
        self.receiver = Receiver(address, self._handle)
        self.timer = Timer()
        self._t0 = time.monotonic()
        self.committed: List[Tuple[int, int]] = []  # (depth, tag) log
        # Commands: the wire identity of a command is (proposer, cmd_index)
        # (simulated_context.rs Command); batch digests from the mempool map
        # onto our own indices so committed local proposals can be resolved
        # back to their transaction batches.
        self.cmd_digests: Dict[int, "object"] = {}
        self._peers = committee.broadcast_addresses(committee.names()[index])
        self._running = False

    def _drain_mempool(self) -> None:
        """CommandFetcher hook: adopt sealed batch digests as the commands
        behind our upcoming proposal indices (bft-driver/src/context.rs
        fetch())."""
        if self.mempool is None:
            return
        next_idx = max([self.cx.next_cmd_index] +
                       [k + 1 for k in self.cmd_digests])
        while True:
            d = self.mempool.try_next_command()
            if d is None:
                break
            self.cmd_digests[next_idx] = d
            next_idx += 1

    def batch_for_command(self, cmd_index: int):
        """Digest of the batch proposed under our command index (if ours)."""
        return self.cmd_digests.get(cmd_index)

    def now(self) -> int:
        return int((time.monotonic() - self._t0) * 1000)

    # -- wire ----------------------------------------------------------------
    def _frame(self, kind: int, pay: E.Payload) -> bytes:
        return json.dumps({
            "kind": kind, "sender": self.index, "pay": _payload_to_json(pay),
        }).encode()

    async def _handle(self, writer: Writer, message: bytes) -> None:
        d = json.loads(message)
        kind = d["kind"]
        sender = d["sender"]
        pay = _payload_from_json(d["pay"], self.p.n_nodes, self.p.chain_k)
        if kind == KIND_NOTIFY:
            should_sync = O.handle_notification(self.p, self.s, self.weights, pay)
            if should_sync:
                req = O.create_request(self.p, self.s)
                await self._send_to(sender, KIND_REQUEST, req)
            await self._update()
        elif kind == KIND_REQUEST:
            resp = O.handle_request(self.p, self.s, self.index, pay)
            await self._send_to(sender, KIND_RESPONSE, resp)
        elif kind == KIND_RESPONSE:
            O.handle_response(self.p, self.s, self.nx, self.cx, self.weights, pay)
            await self._update()

    async def _send_to(self, peer_index: int, kind: int, pay: E.Payload) -> None:
        name = self.committee.names()[peer_index]
        addr = self.committee.address(name)
        if addr:
            await self.sender.send(addr, self._frame(kind, pay))

    async def _broadcast(self, kind: int, pay: E.Payload) -> None:
        await self.sender.broadcast(self._peers, self._frame(kind, pay))

    # -- protocol ------------------------------------------------------------
    async def _update(self) -> None:
        self._drain_mempool()
        before_commits = self.cx.commit_count
        actions = O.update_node(self.p, self.s, self.pm, self.nx, self.cx,
                                self.weights, self.index, self.now(),
                                self.dur_table)
        # Record freshly committed states (StateFinalizer::commit analog).
        # Only the last H entries survive in the ring; start there (a state-
        # sync jump can commit more than H states at once).
        H = self.p.commit_log
        for i in range(max(before_commits, self.cx.commit_count - H),
                       self.cx.commit_count):
            pos = i % H
            self.committed.append(
                (self.cx.log_depth[pos], self.cx.log_tag[pos]))
        notif = O.create_notification(self.p, self.s, self.index)
        if any(actions.send_mask):
            for i, m in enumerate(actions.send_mask):
                if m and i != self.index:
                    await self._send_to(i, KIND_NOTIFY, notif)
        if actions.should_query_all:
            req = O.create_request(self.p, self.s)
            for i in range(self.p.n_nodes):
                if i != self.index:
                    await self._send_to(i, KIND_REQUEST, req)
        if actions.next_sched < E.NEVER:
            self.timer.schedule(max(actions.next_sched, self.now() + 1))
        else:
            self.timer.schedule(self.now() + self.params.delta)

    async def _timer_loop(self) -> None:
        while self._running:
            await self.timer.wait(self.now)
            await self._update()

    async def spawn(self) -> None:
        self._running = True
        await self.receiver.spawn()
        self.timer.schedule(self.now() + 10)
        self._task = asyncio.get_event_loop().create_task(self._timer_loop())

    async def close(self) -> None:
        self._running = False
        self._task.cancel()
        await self.receiver.close()
        self.sender.close()
        self.sig_service.close()
