"""Node binary (/root/reference/node/src/{main,node,config}.rs):

    python -m librabft_simulator_tpu.realnode.node_main keys --filename n0.json
    python -m librabft_simulator_tpu.realnode.node_main run \
        --keys n0.json --committee committee.json --store db0 --parameters p.json

Subcommands mirror the reference CLI: ``keys`` generates a keypair file;
``run`` boots mempool + consensus core.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import sys

from .crypto import PublicKey, SecretKey, generate_keypair
from .driver import ConsensusCore, NodeParameters
from .mempool import Committee, Mempool, Parameters
from .store import Store


def cmd_keys(args):
    pub, sec = generate_keypair()
    with open(args.filename, "w") as f:
        json.dump({"name": pub.to_base64(), "secret": sec.to_base64()}, f, indent=2)
    print(f"wrote {args.filename}")


def load_parameters(path) -> tuple[Parameters, NodeParameters]:
    if not path:
        return Parameters(), NodeParameters()
    with open(path) as f:
        d = json.load(f)
    mp = d.get("mempool", {})
    cs = d.get("consensus", {})
    return (
        Parameters(**mp),
        NodeParameters(**cs),
    )


async def run_node(args):
    with open(args.keys) as f:
        kd = json.load(f)
    name = PublicKey.from_base64(kd["name"])
    secret = SecretKey.from_base64(kd["secret"])
    with open(args.committee) as f:
        committee = Committee.from_json(f.read())
    names = [n.to_base64() for n in committee.names()]
    index = names.index(name.to_base64())
    mp_params, node_params = load_parameters(args.parameters)

    store = Store(f"{args.store}/db.log")
    auth = committee.authorities[name.to_base64()]
    mempool = Mempool(auth.mempool_address, mp_params, store)
    await mempool.spawn()
    core = ConsensusCore(index, committee, secret, node_params, mempool, store,
                         auth.address)
    await core.spawn()
    logging.info("node %d listening on %s", index, auth.address)
    try:
        while True:
            await asyncio.sleep(5)
            print(f"[node {index}] commits={len(core.committed)} "
                  f"round={core.s.current_round}", file=sys.stderr)
    finally:
        await core.close()
        await mempool.close()
        store.close()


def main(argv=None):
    ap = argparse.ArgumentParser(prog="realnode")
    sub = ap.add_subparsers(dest="cmd", required=True)
    k = sub.add_parser("keys", help="generate a keypair file")
    k.add_argument("--filename", required=True)
    r = sub.add_parser("run", help="run a node")
    r.add_argument("--keys", required=True)
    r.add_argument("--committee", required=True)
    r.add_argument("--store", required=True)
    r.add_argument("--parameters", default=None)
    args = ap.parse_args(argv)
    if args.cmd == "keys":
        cmd_keys(args)
    else:
        logging.basicConfig(level=logging.INFO)
        asyncio.run(run_node(args))


if __name__ == "__main__":
    main()
