"""The committed-artifact inventory: every bench/audit JSON at the repo
root, one line each — round, kind, headline metric.

Eighteen rounds of PRs left ~45 committed artifacts (BENCH_*,
KERNEL_CENSUS_*, GRAPH_AUDIT_*, FUZZ_PARITY_*, ...) whose provenance
lives scattered across PERF_NOTES.md prose.  This CLI is the
machine-readable index: it knows each family's headline field and FAILS
LOUD when a recognized artifact is missing it — a truncated or
hand-mangled artifact surfaces here instead of silently rotting.

jax-free by design (safe from any process, no device init):
    python scripts/bench_index.py            # table, sorted by round
    python scripts/bench_index.py --json     # machine-readable
    python scripts/bench_index.py --kind GRAPH_AUDIT

The perf sentinel's BENCH_HISTORY.ndjson rides along as one line
(row count + the latest row's verdicts) — it is NDJSON, not *.json, so
plain JSON globs skip it; this index does not.
"""

import argparse
import glob
import json
import os
import re
import sys

_ROUND_RE = re.compile(r"_r(\d+)")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fmt_rate(v) -> str:
    return f"{float(v):,.0f} events/s"


#: Artifact family -> (filename prefix, headline extractor).  Extractors
#: raise KeyError/TypeError on a missing field — surfaced as the loud
#: per-file error this index exists for.
FAMILIES = (
    ("BENCH_SCALE", lambda d: _fmt_rate(d["events_per_sec"])),
    ("BENCH_SWEEP", lambda d: f"{len(d['configs'])} configs"),
    ("BENCH_TPU_LADDER", lambda d: f"{len(d['ladder'])} ladder rungs"),
    ("BENCH_TPU_SNAPSHOT", lambda d: _fmt_rate(d["events_per_sec"])),
    ("BENCH_MACRO", lambda d: f"{len(d['rungs'])} K-rungs, "
                              f"{len(d['failures'])} failures"),
    # Rounds 1-2 ran before bench.py emitted parseable metrics: ``parsed``
    # is present-but-null there (the tail/rc record the run), a degraded
    # headline — only an absent key is the loud error.
    ("BENCH", lambda d: _fmt_rate(d["parsed"]["events_per_sec"])
     if d["parsed"] is not None else f"no parsed metrics (rc={d['rc']})"),
    ("FUZZ_PARITY", lambda d: f"{d['trials']} trials, "
                              f"{len(d['failures'])} failures"),
    ("KERNEL_CENSUS", lambda d: f"{len(d['modes'])} modes censused"),
    ("GRAPH_AUDIT", lambda d: f"clean={d['clean']}, "
                              f"{d['n_errors']} errors"),
    # r14's ring-ladder flavor carries per-depth rungs; earlier rounds
    # are single-run ledgers — both headline on ttfc, the shared field.
    ("RUNTIME_LEDGER",
     lambda d: (f"{len(d['rungs'])} ring rungs, "
                f"ttfc={d['time_to_first_chunk_s']}s"
                if d.get("flavor") == "ring_dispatch"
                else f"ttfc={d['time_to_first_chunk_s']}s")),
    ("MULTICHIP_FLEET", lambda d: f"{len(d['rungs'])} rungs, "
                                  f"{len(d['failures'])} failures"),
    ("MULTIHOST_FLEET", lambda d: f"{len(d['rungs'])} rungs, "
                                  f"{len(d['failures'])} failures"),
    ("MULTICHIP", lambda d: f"ok={d['ok']}"),
    ("FLEET_TIMELINE", lambda d: f"{len(d['rungs'])} rungs, "
                                 f"registry v{d['registry_version']}"),
    ("BASELINE", lambda d: f"metric: {d['metric']}"),
)


def classify(name: str):
    """(kind, round) for one artifact filename; round is None for
    un-rounded files (BASELINE.json), kind is None when unrecognized."""
    stem = name[:-len(".json")] if name.endswith(".json") else name
    m = _ROUND_RE.search(stem)
    rnd = int(m.group(1)) if m else None
    for prefix, _ in FAMILIES:
        if stem == prefix or stem.startswith(prefix + "_"):
            return prefix, rnd
    return None, rnd


def _extract(kind: str, data: dict) -> str:
    fn = dict(FAMILIES)[kind]
    return fn(data)


def index_rows(root: str) -> tuple[list, list]:
    """Scan ``root`` -> (rows, errors).  Each row:
    ``{"file", "kind", "round", "headline"}``; each error a string."""
    rows, errors = [], []
    for path in sorted(glob.glob(os.path.join(root, "*.json"))):
        name = os.path.basename(path)
        kind, rnd = classify(name)
        if kind is None:
            rows.append({"file": name, "kind": "?", "round": rnd,
                         "headline": "(unrecognized family)"})
            continue
        try:
            with open(path) as f:
                data = json.load(f)
        except ValueError as e:
            errors.append(f"{name}: unparseable JSON ({e})")
            continue
        try:
            headline = _extract(kind, data)
        except (KeyError, TypeError, IndexError) as e:
            errors.append(f"{name}: recognized as {kind} but missing its "
                          f"headline field ({e!r}) — truncated or "
                          f"hand-edited artifact?")
            continue
        rows.append({"file": name, "kind": kind, "round": rnd,
                     "headline": headline})

    hist = os.path.join(root, "BENCH_HISTORY.ndjson")
    if os.path.exists(hist):
        bench = []
        try:
            with open(hist) as f:
                for ln in f:
                    if ln.strip():
                        bench.append(json.loads(ln))
        except ValueError:
            errors.append("BENCH_HISTORY.ndjson: unparseable row")
            bench = []
        bench = [r for r in bench if r.get("kind") == "bench"]
        if bench:
            try:
                last = bench[-1]
                worst = ("regress" if "regress" in last["verdicts"].values()
                         else "ok")
                rows.append({"file": "BENCH_HISTORY.ndjson",
                             "kind": "BENCH_HISTORY", "round": None,
                             "headline": f"{len(bench)} rows, latest "
                                         f"{len(last['rungs'])} rungs "
                                         f"-> {worst}"})
            except (KeyError, TypeError) as e:
                errors.append(f"BENCH_HISTORY.ndjson: bench row missing "
                              f"its headline field ({e!r})")
    return rows, errors


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="inventory the committed bench/audit artifacts")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--kind", default=None,
                    help="only artifacts of this family prefix")
    ap.add_argument("--root", default=repo_root(),
                    help="directory to scan (default: the repo root)")
    args = ap.parse_args(argv)

    rows, errors = index_rows(args.root)
    if args.kind:
        rows = [r for r in rows if r["kind"] == args.kind]
    rows.sort(key=lambda r: (r["round"] if r["round"] is not None else -1,
                             r["file"]))
    if args.json:
        print(json.dumps({"artifacts": rows, "errors": errors}, indent=1))
    else:
        for r in rows:
            rnd = f"r{r['round']:02d}" if r["round"] is not None else "  -"
            print(f"{rnd}  {r['kind']:16s} {r['file']:36s} {r['headline']}")
        print(f"{len(rows)} artifacts")
    for e in errors:
        print(f"bench_index: ERROR {e}", file=sys.stderr)
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
