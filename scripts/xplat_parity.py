"""On-chip determinism check: TPU trajectories must equal CPU leaf-for-leaf.

This is the script that caught the axon-stack batched-scalar-scatter
miscompile (see scripts/tpu_scatter_bug_repro.py and PERF_NOTES.md): the
engine was bit-exact at B=64 and silently wrong at B=2048, so ALWAYS run
this at fleet batch sizes after any engine or stack change.

Usage (tunnel up):
    python scripts/xplat_parity.py                 # serial B=2048, 2x96 steps
    python scripts/xplat_parity.py parallel 1024 16 2
    python scripts/xplat_parity.py serial 16384 64 2
    # Wide-fleet parallel shapes (the sweep's config-3/5 lowerings: lane
    # routing + flat inbox scatters at n=16/64 widths):
    XPLAT_NODES=64 XPLAT_DELAY=pareto XPLAT_DROP=0.05 \
        python scripts/xplat_parity.py parallel 64 8 2
    XPLAT_NODES=16 XPLAT_CHAIN=2 python scripts/xplat_parity.py parallel 256 8 2

Exit code 0 and {"n_bad": 0} means every state leaf of the TPU run equals
the CPU run.  Nonzero n_bad prints the first mismatched leaf paths.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402


def _setup_process():
    """Stack limit + persistent compile cache.  Called from run_check, NOT
    at module import: tests/test_xplat_parity.py imports this module during
    pytest collection, and module-level jax.config mutations would override
    the tier-1 suite's cache configuration for the whole session.  The
    cache config is also applied only when nothing configured one yet —
    under pytest, conftest.py already owns it and run_check must not
    repoint the rest of the session."""
    from librabft_simulator_tpu.utils.cache import setup_compile_cache
    from librabft_simulator_tpu.utils.rlimit import raise_stack_limit

    raise_stack_limit()
    # force=False: under pytest, conftest.py already owns the cache config
    # and run_check must not repoint the rest of the session.
    setup_compile_cache()


def run_check(engine_name: str = "serial", batch: int = 2048,
              chunk: int = 96, calls: int = 2, n_nodes: int = 4,
              delay_kind: str = "uniform", drop_prob: float = 0.0,
              commit_chain: int = 3) -> dict:
    """Run the same fleet on the accelerator and on CPU; diff every leaf.

    Returns the result dict (``n_bad == 0`` means bit-exact).  Also the
    entry point for ``tests/test_xplat_parity.py``, which runs the open
    n=16/64 wide-lowering shapes whenever a chip is visible."""
    from librabft_simulator_tpu.core.types import SimParams
    from librabft_simulator_tpu.sim import parallel_sim, simulator
    from librabft_simulator_tpu.utils import xops

    _setup_process()
    engine = parallel_sim if engine_name == "parallel" else simulator
    n = n_nodes
    p = SimParams(n_nodes=n, delay_kind=delay_kind, drop_prob=drop_prob,
                  commit_chain=commit_chain,
                  max_clock=2**30, epoch_handoff=False,
                  queue_cap=max(32, 4 * n))
    # Resolve the 'auto' lowering forms ONCE, against the process default
    # backend (the chip, when one is visible): BOTH legs then run the SAME
    # program — packed planes + dense writes on a TPU host — on two
    # backends.  That is this tool's contract (catch backend miscompiles
    # of the graph the chip actually runs, like the round-5 scalar-scatter
    # bug); the semantic equivalence of the TPU forms against the proven
    # CPU forms is pinned separately by tests/test_packing.py,
    # tests/test_xops.py, and the fuzz campaign on CPU.
    p = xops.resolve_params(p)

    def runit(device):
        with jax.default_device(device):
            st = engine.init_batch(p, np.arange(batch, dtype=np.uint32))
            st = simulator.dedupe_buffers(st)
            run = engine.make_run_fn(p, chunk)
            for _ in range(calls):
                st = run(st)
            return jax.device_get(st)

    tpus = [d for d in jax.devices() if d.platform != "cpu"]
    if not tpus:
        return {"error": "no accelerator device visible"}
    t = runit(tpus[0])
    c = runit(jax.devices("cpu")[0])
    bad = ["/".join(str(q) for q in pt)
           for (pt, lt), (_, lc) in zip(
               jax.tree_util.tree_flatten_with_path(t)[0],
               jax.tree_util.tree_flatten_with_path(c)[0])
           if not np.array_equal(np.asarray(lt), np.asarray(lc))]
    return {
        "engine": engine_name, "n_nodes": n, "instances": batch,
        "steps": chunk * calls, "n_bad": len(bad), "bad": bad[:10],
        "commits_tpu": int(np.sum(t.ctx.commit_count)),
        "commits_cpu": int(np.sum(c.ctx.commit_count)),
    }


def main() -> int:
    out = run_check(
        engine_name=sys.argv[1] if len(sys.argv) > 1 else "serial",
        batch=int(sys.argv[2]) if len(sys.argv) > 2 else 2048,
        chunk=int(sys.argv[3]) if len(sys.argv) > 3 else 96,
        calls=int(sys.argv[4]) if len(sys.argv) > 4 else 2,
        n_nodes=int(os.environ.get("XPLAT_NODES", "4")),
        delay_kind=os.environ.get("XPLAT_DELAY", "uniform"),
        drop_prob=float(os.environ.get("XPLAT_DROP", "0")),
        commit_chain=int(os.environ.get("XPLAT_CHAIN", "3")))
    print(json.dumps(out))
    if "error" in out:
        return 2
    return 0 if not out["n_bad"] else 1


if __name__ == "__main__":
    sys.exit(main())
