"""On-chip determinism check: TPU trajectories must equal CPU leaf-for-leaf.

This is the script that caught the axon-stack batched-scalar-scatter
miscompile (see scripts/tpu_scatter_bug_repro.py and PERF_NOTES.md): the
engine was bit-exact at B=64 and silently wrong at B=2048, so ALWAYS run
this at fleet batch sizes after any engine or stack change.

Usage (tunnel up):
    python scripts/xplat_parity.py                 # serial B=2048, 2x96 steps
    python scripts/xplat_parity.py parallel 1024 16 2
    python scripts/xplat_parity.py serial 16384 64 2
    # Wide-fleet parallel shapes (the sweep's config-3/5 lowerings: lane
    # routing + flat inbox scatters at n=16/64 widths):
    XPLAT_NODES=64 XPLAT_DELAY=pareto XPLAT_DROP=0.05 \
        python scripts/xplat_parity.py parallel 64 8 2
    XPLAT_NODES=16 XPLAT_CHAIN=2 python scripts/xplat_parity.py parallel 256 8 2

Exit code 0 and {"n_bad": 0} means every state leaf of the TPU run equals
the CPU run.  Nonzero n_bad prints the first mismatched leaf paths.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from librabft_simulator_tpu.utils.rlimit import raise_stack_limit

raise_stack_limit()

import jax  # noqa: E402
import numpy as np  # noqa: E402

os.makedirs("/tmp/librabft_tpu_jax_cache", exist_ok=True)
jax.config.update("jax_compilation_cache_dir", "/tmp/librabft_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def main() -> int:
    from librabft_simulator_tpu.core.types import SimParams
    from librabft_simulator_tpu.sim import parallel_sim, simulator

    engine_name = sys.argv[1] if len(sys.argv) > 1 else "serial"
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 2048
    chunk = int(sys.argv[3]) if len(sys.argv) > 3 else 96
    calls = int(sys.argv[4]) if len(sys.argv) > 4 else 2
    engine = parallel_sim if engine_name == "parallel" else simulator
    n = int(os.environ.get("XPLAT_NODES", "4"))
    p = SimParams(n_nodes=n,
                  delay_kind=os.environ.get("XPLAT_DELAY", "uniform"),
                  drop_prob=float(os.environ.get("XPLAT_DROP", "0")),
                  commit_chain=int(os.environ.get("XPLAT_CHAIN", "3")),
                  max_clock=2**30, epoch_handoff=False,
                  queue_cap=max(32, 4 * n))

    def runit(device):
        with jax.default_device(device):
            st = engine.init_batch(p, np.arange(batch, dtype=np.uint32))
            st = simulator.dedupe_buffers(st)
            run = engine.make_run_fn(p, chunk)
            for _ in range(calls):
                st = run(st)
            return jax.device_get(st)

    tpus = [d for d in jax.devices() if d.platform != "cpu"]
    if not tpus:
        print(json.dumps({"error": "no accelerator device visible"}))
        return 2
    t = runit(tpus[0])
    c = runit(jax.devices("cpu")[0])
    bad = ["/".join(str(q) for q in pt)
           for (pt, lt), (_, lc) in zip(
               jax.tree_util.tree_flatten_with_path(t)[0],
               jax.tree_util.tree_flatten_with_path(c)[0])
           if not np.array_equal(np.asarray(lt), np.asarray(lc))]
    print(json.dumps({
        "engine": engine_name, "n_nodes": n, "instances": batch,
        "steps": chunk * calls, "n_bad": len(bad), "bad": bad[:10],
        "commits_tpu": int(np.sum(t.ctx.commit_count)),
        "commits_cpu": int(np.sum(c.ctx.commit_count)),
    }))
    return 0 if not bad else 1


if __name__ == "__main__":
    sys.exit(main())
