"""DEPRECATED dev probe: window occupancy + throughput, parallel vs serial.

The measurement itself moved into the telemetry exporter —
``librabft_simulator_tpu.telemetry.report.probe_occupancy`` — so sweeps and
future tooling can call it directly; this script remains as a thin CLI
wrapper (plus the timing-only ablation hooks, which monkeypatch internals
and stay a dev-script concern).

Run on CPU: JAX_PLATFORMS=cpu python scripts/occupancy_probe.py
Env: PN (nodes) PB (batch) PCHUNK PREPS PDELAY PQCAP PDROP PA (lanes)
PK (drain) ENGINES=parallel,serial ABLATE=<piece> PTEL=1 (telemetry block)
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.sim import parallel_sim as P
from librabft_simulator_tpu.sim import simulator as S
from librabft_simulator_tpu.telemetry import report as tel_report


def probe(engine, name, p, B=512, chunk=None, reps=None):
    chunk = chunk or int(os.environ.get("PCHUNK", "32"))
    reps = reps or int(os.environ.get("PREPS", "3"))
    r = tel_report.probe_occupancy(engine, p, B=B, chunk=chunk, reps=reps)
    print(f"{name:10s} ev/s={r['events_per_sec']:10.0f} "
          f"rounds/s={r['rounds_per_sec']:8.0f} "
          f"occupancy={r['occupancy']:5.2f} compile={r['compile_s']:5.1f}s "
          f"dt={r['elapsed_s']:.2f}s ovf={r['overflow_frac']:.3f} "
          f"commits={r['commits']}")
    if "telemetry" in r:
        print(f"{'':10s} telemetry: {r['telemetry']}")


def ablate(name):
    """Stub out one piece of the step machinery to attribute cost.
    Trajectories become WRONG; timing-only."""
    from librabft_simulator_tpu.core import data_sync as ds
    from librabft_simulator_tpu.core import node as node_ops

    if name == "timeouts":
        ds._insert_timeout_batch = lambda p, s, w, to_msg, rec_epoch: s
    elif name == "response":
        ds.handle_response = lambda p, s, nx, cx, w, pay: (s, nx, cx)
    elif name == "notification":
        import jax.numpy as jnp
        ds.handle_notification = lambda p, s, w, pay: (s, jnp.bool_(False))
    elif name == "request":
        ds.handle_request = lambda p, s, a, req, notif=None: (
            notif if notif is not None else ds.create_notification(p, s, a))
    elif name == "commits":
        import jax.numpy as jnp
        from librabft_simulator_tpu.core.types import payload_width

        def _stub_commits(p, s, nx, ctx, w, author=0):
            F = payload_width(p) if p.epoch_handoff else 0
            return (s, nx, ctx, jnp.bool_(False), s.epoch_id,
                    jnp.zeros((F,), jnp.int32))
        node_ops.process_commits = _stub_commits
    elif name == "update":
        def _stub_update(p, s, pm, nx, cx, w, a, clock, dur):
            import jax.numpy as jnp
            from librabft_simulator_tpu.core.types import payload_width
            n = p.n_nodes
            F = payload_width(p) if p.epoch_handoff else 0
            return s, pm, nx, cx, node_ops.NodeUpdateActions(
                next_sched=jnp.asarray(clock + 10, jnp.int32),
                send_mask=jnp.zeros((n,), jnp.bool_),
                should_query_all=jnp.bool_(False),
                ho_switched=jnp.bool_(False),
                ho_epoch=s.epoch_id,
                ho_pack=jnp.zeros((F,), jnp.int32))
        node_ops.update_node = _stub_update
    elif name:
        raise ValueError(name)


if __name__ == "__main__":
    n = int(os.environ.get("PN", "4"))
    B = int(os.environ.get("PB", "512"))
    ab = os.environ.get("ABLATE", "")
    engines = os.environ.get("ENGINES", "parallel,serial").split(",")
    ablate(ab)
    p = SimParams(
        n_nodes=n, delay_kind=os.environ.get("PDELAY", "uniform"),
        max_clock=2**30,
        queue_cap=int(os.environ.get("PQCAP", str(max(32, 4 * n)))),
        drop_prob=float(os.environ.get("PDROP", "0")),
        active_lanes=int(os.environ.get("PA", "0")),
        drain_k=int(os.environ.get("PK", "0")),
        telemetry=os.environ.get("PTEL", "") == "1")
    for e in engines:
        probe({"parallel": P, "serial": S}[e], f"{e}{'/' + ab if ab else ''}",
              p, B=B)
