"""Dev probe: window occupancy + throughput of the parallel engine vs serial.

Run on CPU: JAX_PLATFORMS=cpu python scripts/occupancy_probe.py
"""
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.sim import parallel_sim as P
from librabft_simulator_tpu.sim import simulator as S
from librabft_simulator_tpu.sim.simulator import dedupe_buffers


def probe(engine, name, p, B=512, chunk=None, reps=None):
    chunk = chunk or int(os.environ.get("PCHUNK", "32"))
    reps = reps or int(os.environ.get("PREPS", "3"))
    seeds = np.arange(B, dtype=np.uint32)
    st = dedupe_buffers(engine.init_batch(p, seeds))
    run = engine.make_run_fn(p, chunk)
    t0 = time.perf_counter()
    st = run(st)
    jax.block_until_ready(st)
    compile_s = time.perf_counter() - t0
    e0 = int(np.sum(jax.device_get(st.n_events)))
    r0 = int(np.sum(np.max(jax.device_get(st.store.current_round), axis=-1) - 1))
    t0 = time.perf_counter()
    for _ in range(reps):
        st = run(st)
    jax.block_until_ready(st)
    dt = time.perf_counter() - t0
    e1 = int(np.sum(jax.device_get(st.n_events)))
    r1 = int(np.sum(np.max(jax.device_get(st.store.current_round), axis=-1) - 1))
    lost_f = st.n_queue_full if hasattr(st, "n_queue_full") else st.n_inbox_full
    lost = int(np.sum(jax.device_get(lost_f)))
    sent = int(np.sum(jax.device_get(st.n_msgs_sent)))
    com = int(np.sum(jax.device_get(st.ctx.commit_count)))
    steps = chunk * reps * B
    print(f"{name:10s} ev/s={(e1-e0)/dt:10.0f} rounds/s={(r1-r0)/dt:8.0f} "
          f"occupancy={(e1-e0)/steps:5.2f} compile={compile_s:5.1f}s "
          f"dt={dt:.2f}s ovf={lost/max(lost+sent,1):.3f} commits={com}")


def ablate(name):
    """Stub out one piece of the step machinery to attribute cost.
    Trajectories become WRONG; timing-only."""
    from librabft_simulator_tpu.core import data_sync as ds
    from librabft_simulator_tpu.core import node as node_ops

    if name == "timeouts":
        ds._insert_timeout_batch = lambda p, s, w, to_msg, rec_epoch: s
    elif name == "response":
        ds.handle_response = lambda p, s, nx, cx, w, pay: (s, nx, cx)
    elif name == "notification":
        import jax.numpy as jnp
        ds.handle_notification = lambda p, s, w, pay: (s, jnp.bool_(False))
    elif name == "request":
        ds.handle_request = lambda p, s, a, req, notif=None: (
            notif if notif is not None else ds.create_notification(p, s, a))
    elif name == "commits":
        import jax.numpy as jnp
        from librabft_simulator_tpu.core.types import payload_width

        def _stub_commits(p, s, nx, ctx, w, author=0):
            F = payload_width(p) if p.epoch_handoff else 0
            return (s, nx, ctx, jnp.bool_(False), s.epoch_id,
                    jnp.zeros((F,), jnp.int32))
        node_ops.process_commits = _stub_commits
    elif name == "update":
        def _stub_update(p, s, pm, nx, cx, w, a, clock, dur):
            import jax.numpy as jnp
            from librabft_simulator_tpu.core.types import payload_width
            n = p.n_nodes
            F = payload_width(p) if p.epoch_handoff else 0
            return s, pm, nx, cx, node_ops.NodeUpdateActions(
                next_sched=jnp.asarray(clock + 10, jnp.int32),
                send_mask=jnp.zeros((n,), jnp.bool_),
                should_query_all=jnp.bool_(False),
                ho_switched=jnp.bool_(False),
                ho_epoch=s.epoch_id,
                ho_pack=jnp.zeros((F,), jnp.int32))
        node_ops.update_node = _stub_update
    elif name:
        raise ValueError(name)


if __name__ == "__main__":
    n = int(os.environ.get("PN", "4"))
    B = int(os.environ.get("PB", "512"))
    ab = os.environ.get("ABLATE", "")
    engines = os.environ.get("ENGINES", "parallel,serial").split(",")
    ablate(ab)
    p = SimParams(
        n_nodes=n, delay_kind=os.environ.get("PDELAY", "uniform"),
        max_clock=2**30,
        queue_cap=int(os.environ.get("PQCAP", str(max(32, 4 * n)))),
        drop_prob=float(os.environ.get("PDROP", "0")),
        active_lanes=int(os.environ.get("PA", "0")),
        drain_k=int(os.environ.get("PK", "0")))
    for e in engines:
        probe({"parallel": P, "serial": S}[e], f"{e}{'/' + ab if ab else ''}",
              p, B=B)
