"""Live fleet-health view over a --stream-out NDJSON file.

The fleet runtime's per-chunk digest poll (telemetry/stream.py) costs zero
extra host syncs; pointing a TimelineRecorder at a file
(``run_sharded(..., stream=TimelineRecorder(p, out=PATH))``, or
``BENCH_STREAM=1 python bench.py`` / ``sweeps --stream-out PATH``) makes
that stream observable from ANOTHER terminal while the run is still going:

    python scripts/fleet_watch.py /tmp/fleet.ndjson            # follow live
    python scripts/fleet_watch.py /tmp/fleet.ndjson --once     # print + exit
    python scripts/fleet_watch.py /tmp/fleet.ndjson --summary  # final digest
    python scripts/fleet_watch.py /tmp/ledger.ndjson --ledger  # host ledger
    python scripts/fleet_watch.py /tmp/serve.ndjson --serve    # admission view

One line per polled chunk: halt progress (padding-corrected when the
runner emitted a fleet meta line), events/s, commit/drop/overflow counts,
queue pressure, round span, ETA — and a loud ``WATCHDOG`` column the
moment any in-graph detector (liveness stall, queue saturation, sync-jump
anomaly, safety violation) trips.  Reads are registry-version-checked
(stream.load_ndjson refuses artifacts from another slot-map version), so
a stale viewer can never silently misread a newer stream.  Partially
written files are fine: a mid-write trailing line is skipped, and an
empty/meta-less file exits with a clear message instead of a traceback.

``--serve`` reads a resident-fleet SERVICE stream (serve/service.py,
``LIBRABFT_SERVE_OUT`` / ``FleetService(out=...)``): the admission-queue
view — pending/admitted/egressed counts, slot occupancy, and per-request
ttfc (admission → first polled chunk) as requests flow through, plus the
digest heartbeat.  Same hardening as every other mode: an empty, foreign,
or meta-less file exits 1 with a message, never a traceback.

``--ledger`` reads a RUNTIME-LEDGER stream instead (telemetry/ledger.py,
``LIBRABFT_LEDGER_OUT``): per-chunk dispatch-enqueue vs blocking-poll
wall time for every recorded host loop, the measured pipeline-overlap
fraction of the double-buffered dispatch, dispatch-queue bubbles, the
time-to-first-chunk headline, and the compile ledger (per structural
key, with persistent-cache hit/miss).

No jax import anywhere: the viewer is pure host-side and starts instantly.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from librabft_simulator_tpu.telemetry import ledger as tledger  # noqa: E402
from librabft_simulator_tpu.telemetry import report as treport  # noqa: E402
from librabft_simulator_tpu.telemetry import stream as tstream  # noqa: E402


def _flag_names(flags: int) -> str:
    names = [d for i, d in enumerate(tstream.WD_DETECTORS)
             if flags & (1 << i)]
    return ",".join(names) if names else "-"


class _View:
    """Stateful row formatter: meta/fleet lines adjust the header and the
    padding correction; row lines print one status line each."""

    def __init__(self, out=sys.stdout):
        self.out = out
        self.total = None     # padded instance count (digest's halted basis)
        self.padding = 0
        self.header_done = False

    def _header(self):
        print(f"{'chunk':>5} {'t_s':>8} {'halted':>12} {'events':>10} "
              f"{'ev/s':>10} {'commits':>8} {'drop':>6} {'ovfl':>6} "
              f"{'qmax':>5} {'rounds':>11} {'eta_s':>8}  WATCHDOG",
              file=self.out)
        self.header_done = True

    def feed(self, obj: dict) -> None:
        kind = obj.get("kind")
        if kind == "meta":
            treport.require_registry_version(obj.get("registry_version"),
                                             what="stream")
            print(f"# fleet stream: n_nodes={obj.get('n_nodes')} "
                  f"watchdog={'on' if obj.get('watchdog') else 'off'} "
                  f"registry v{obj.get('registry_version')}", file=self.out)
            if obj.get("total_instances"):
                self.total = int(obj["total_instances"])
            return
        if kind == "fleet":
            self.total = int(obj["total_instances"])
            self.padding = int(obj.get("padding", 0))
            if self.padding:
                print(f"# fleet: {obj['n_valid']} instances "
                      f"(+{self.padding} pre-halted padding)", file=self.out)
            return
        if kind != "row":
            return
        if not self.header_done:
            self._header()
        halted = obj["halted"] - self.padding
        denom = (self.total - self.padding) if self.total else None
        halt = f"{halted}/{denom}" if denom else f"{halted}"
        rounds = f"{obj['committed_round_min']}..{obj['committed_round_max']}"
        eta = obj.get("eta_s")
        flags = obj.get("watchdog_flags", 0)
        line = (f"{obj['chunk']:>5} {obj['t_s']:>8.2f} {halt:>12} "
                f"{obj['events']:>10} {obj['ev_per_s']:>10.1f} "
                f"{obj['commits']:>8} {obj['drops']:>6} {obj['overflow']:>6} "
                f"{obj['queue_depth_max']:>5} {rounds:>11} "
                f"{eta if eta is not None else '-':>8}  "
                f"{_flag_names(flags)}")
        print(line, file=self.out, flush=True)


def follow(path: str, view: _View, poll_s: float = 0.5,
           idle_timeout_s: float | None = None) -> None:
    """Tail the NDJSON file live: feed every complete line as it lands,
    keep waiting for more (a run in progress appends between polls).
    Stops after ``idle_timeout_s`` with no new data (None = forever)."""
    idle = 0.0
    with open(path) as f:
        buf = ""
        while True:
            chunk = f.read()
            if chunk:
                idle = 0.0
                buf += chunk
                while "\n" in buf:
                    line, buf = buf.split("\n", 1)
                    if line.strip():
                        view.feed(json.loads(line))
            else:
                idle += poll_s
                if idle_timeout_s is not None and idle >= idle_timeout_s:
                    return
                time.sleep(poll_s)


def show_ledger(path: str, out=None) -> int:
    """The --ledger view: per-chunk dispatch/poll wall time for every
    recorded host loop, the measured overlap fraction + bubbles of the
    double-buffered dispatch, time_to_first_chunk, and the compile
    ledger (key, shapes, compile seconds, persistent-cache verdict)."""
    out = out if out is not None else sys.stdout  # late-bound: capturable
    meta, rows = tledger.load_ndjson(path)
    run_meta = {r["run"]: r for r in rows if r.get("kind") == "run"}
    runs = sorted(run_meta) or sorted(
        {r["run"] for r in rows
         if r.get("kind") == "span" and r.get("run") is not None})
    printed = False
    for rid in runs:
        pipe = tledger.pipeline_stats(rows, run=rid)
        if not pipe["chunks"]:
            continue
        printed = True
        rm = run_meta.get(rid, {})
        # Overlap is only meaningful for a double-buffered loop (the run
        # row says pipeline=True); a serial completion loop polls the
        # chunk it just dispatched, so its ~1.0 would be a lie.
        overlap = (pipe["overlap_fraction"] if rm.get("pipeline")
                   else "n/a (not double-buffered)")
        print(f"# run {rid} ({rm.get('label', '?')}): "
              f"chunks={pipe['chunks']} "
              f"overlap={overlap} "
              f"bubbles={pipe['bubble_count']} "
              f"time_to_first_chunk={pipe.get('time_to_first_chunk_s')}s",
              file=out)
        print(f"{'chunk':>5} {'dispatch_ms':>12} {'poll_ms':>9}  note",
              file=out)
        for row in pipe["rows"]:
            note = "bubble" if row["chunk"] in pipe["bubbles"] else (
                "cold (compile)" if row["chunk"] == 0 else "")
            print(f"{row['chunk']:>5} {row['dispatch_s'] * 1e3:>12.2f} "
                  f"{row['poll_s'] * 1e3:>9.2f}  {note}", file=out)
    compiles = [r for r in rows if r.get("kind") == "compile"]
    if compiles:
        printed = True
        aot_hits = sum(1 for e in compiles if e.get("cache") == "aot-hit")
        aot_stale = sum(1 for e in compiles if e.get("cache") == "aot-stale")
        print(f"# compile ledger: {len(compiles)} builds"
              + (f" ({aot_hits} aot-hit)" if aot_hits else "")
              + (f" ({aot_stale} AOT-STALE — rebuild the store: "
                 f"scripts/warm_cache.py)" if aot_stale else ""), file=out)
        for e in compiles:
            # aot-hit entries paid deserialize seconds, not a compile;
            # aot-stale entries name the fallback verdict they fell to.
            if e.get("cache") == "aot-hit":
                cost = f"aot_load_s={e.get('aot_load_s', 0):.2f}"
            else:
                cost = f"compile_s={e.get('compile_s', 0):.2f}"
            verdict = e.get("cache")
            if e.get("fallback"):
                verdict = f"{verdict}->{e['fallback']}"
            print(f"  {e.get('key')} {e.get('engine', '?'):>14} "
                  f"shapes={e.get('shapes')} {verdict} {cost} "
                  f"first_call_s={e.get('first_call_s', 0):.2f}", file=out)
    if not printed:
        print("no ledger rows yet", file=sys.stderr)
        return 1
    return 0


class _ServeView:
    """The --serve formatter: request-lifecycle rows as an event log,
    digest rows as a compact occupancy heartbeat."""

    def __init__(self, out=sys.stdout):
        self.out = out
        self.slots = None
        self.last: dict = {}
        self.header_done = False

    def _header(self):
        print(f"{'t_s':>8} {'event':>11} {'request':>10} {'slot':>5} "
              f"{'ttfc_s':>8} {'pend':>5} {'actv':>5} {'done':>5}  detail",
              file=self.out)
        self.header_done = True

    def feed(self, obj: dict) -> None:
        kind = obj.get("kind")
        if kind == "meta":
            treport.require_registry_version(obj.get("registry_version"),
                                             what="serve stream")
            if not obj.get("serve"):
                raise ValueError(
                    "not a serve stream (no serve marker in the meta "
                    "line); plain digest streams want the default view")
            self.slots = obj.get("slots")
            print(f"# resident fleet: {self.slots} slots x "
                  f"chunk {obj.get('chunk')} (n_nodes={obj.get('n_nodes')},"
                  f" registry v{obj.get('registry_version')})",
                  file=self.out)
            return
        if kind == "request":
            if not self.header_done:
                self._header()
            self.last = obj
            ttfc = obj.get("ttfc_s")
            detail = ""
            if obj.get("event") == "egressed":
                res = obj.get("result") or {}
                detail = (f"events={res.get('events')} "
                          f"commits={res.get('commits')} "
                          f"safe={res.get('safe')} "
                          f"latency_s={obj.get('latency_s')}")
            print(f"{obj.get('t_s', 0):>8.2f} {obj.get('event', '?'):>11} "
                  f"{str(obj.get('id')):>10} "
                  f"{str(obj.get('slot', '-')):>5} "
                  f"{ttfc if ttfc is not None else '-':>8} "
                  f"{obj.get('pending', 0):>5} {obj.get('active', 0):>5} "
                  f"{obj.get('egressed', 0):>5}  {detail}",
                  file=self.out, flush=True)
            return
        if kind == "row":
            if not self.header_done:
                self._header()
            occ = (f"occupancy {self.last.get('active', '?')}/{self.slots}"
                   if self.slots else "")
            print(f"{obj.get('t_s', 0):>8.2f} {'chunk':>11} "
                  f"{'':>10} {'':>5} {'':>8} "
                  f"{self.last.get('pending', 0):>5} "
                  f"{self.last.get('active', 0):>5} "
                  f"{self.last.get('egressed', 0):>5}  "
                  f"halted={obj.get('halted')} events={obj.get('events')} "
                  f"{occ}", file=self.out, flush=True)


def show_serve(path: str, out=None) -> int:
    """The --serve one-shot view (exit 1 on empty/foreign files)."""
    out = out if out is not None else sys.stdout
    meta, rows = tstream.load_ndjson(path)
    view = _ServeView(out=out)
    view.feed(dict(meta, kind="meta"))
    events = [r for r in rows if r.get("kind") == "request"]
    if not events:
        print("no request rows yet", file=sys.stderr)
        return 1
    for r in rows:
        if r.get("kind") == "request":
            view.feed(r)
    # Closing occupancy summary from the newest row.
    last = events[-1]
    print(f"# pending={last.get('pending')} active={last.get('active')} "
          f"egressed={last.get('egressed')} of {meta.get('slots')} slots",
          file=out)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("path", help="NDJSON stream file (TimelineRecorder out=)")
    ap.add_argument("--once", action="store_true",
                    help="print what's in the file now and exit")
    ap.add_argument("--summary", action="store_true",
                    help="print only the final digest as JSON and exit")
    ap.add_argument("--ledger", action="store_true",
                    help="the file is a runtime-ledger stream "
                         "(LIBRABFT_LEDGER_OUT): print per-chunk "
                         "dispatch/poll timing, overlap, bubbles, and "
                         "the compile ledger")
    ap.add_argument("--serve", action="store_true",
                    help="the file is a resident-fleet service stream "
                         "(serve/; LIBRABFT_SERVE_OUT): print the "
                         "admission-queue event log — pending/admitted/"
                         "egressed counts, slot occupancy, per-request "
                         "ttfc — plus the digest heartbeat; --once/"
                         "default follow both work")
    ap.add_argument("--poll", type=float, default=0.5,
                    help="follow-mode poll interval in seconds")
    ap.add_argument("--idle-timeout", type=float, default=None,
                    help="stop following after this many idle seconds")
    args = ap.parse_args(argv)

    try:
        if args.ledger:
            return show_ledger(args.path)

        if args.serve:
            if args.once or args.summary:
                return show_serve(args.path)
            view = _ServeView()
            follow(args.path, view, poll_s=args.poll,
                   idle_timeout_s=args.idle_timeout)
            return 0

        if args.summary:
            meta, rows = tstream.load_ndjson(args.path)
            data = [r for r in rows if r.get("kind") == "row"]
            if not data:
                print("no rows yet", file=sys.stderr)
                return 1
            last = data[-1]
            print(json.dumps({
                "chunks": len(data), "elapsed_s": last["t_s"],
                "final": {n: last[n] for n, _ in tstream.DIGEST_SLOTS},
                "watchdog_flags": last["watchdog_flags"],
                "watchdog": _flag_names(last["watchdog_flags"]),
            }, indent=1))
            return 0

        view = _View()
        if args.once:
            meta, rows = tstream.load_ndjson(args.path)
            view.feed(dict(meta, kind="meta"))
            for r in rows:
                view.feed(r)
            return 0
        follow(args.path, view, poll_s=args.poll,
               idle_timeout_s=args.idle_timeout)
    except (OSError, ValueError) as e:
        # An empty, still-initializing, or foreign file is an operator
        # answer ("nothing to show yet / wrong file"), not a traceback.
        print(f"fleet_watch: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
