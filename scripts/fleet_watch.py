"""Live fleet-health view over a --stream-out NDJSON file.

The fleet runtime's per-chunk digest poll (telemetry/stream.py) costs zero
extra host syncs; pointing a TimelineRecorder at a file
(``run_sharded(..., stream=TimelineRecorder(p, out=PATH))``, or
``BENCH_STREAM=1 python bench.py`` / ``sweeps --stream-out PATH``) makes
that stream observable from ANOTHER terminal while the run is still going:

    python scripts/fleet_watch.py /tmp/fleet.ndjson            # follow live
    python scripts/fleet_watch.py /tmp/fleet.ndjson --once     # print + exit
    python scripts/fleet_watch.py /tmp/fleet.ndjson --summary  # final digest
    python scripts/fleet_watch.py /tmp/ledger.ndjson --ledger  # host ledger
    python scripts/fleet_watch.py /tmp/serve.ndjson --serve    # admission view
    python scripts/fleet_watch.py 'wd/ledger-p*.ndjson' \
        --timeline --out merged.json   # ONE clock-aligned Perfetto trace

One line per polled chunk: halt progress (padding-corrected when the
runner emitted a fleet meta line), events/s, commit/drop/overflow counts,
queue pressure, round span, ETA — and a loud ``WATCHDOG`` column the
moment any in-graph detector (liveness stall, queue saturation, sync-jump
anomaly, safety violation) trips.  Reads are registry-version-checked
(stream.load_ndjson refuses artifacts from another slot-map version), so
a stale viewer can never silently misread a newer stream.  Partially
written files are fine: a mid-write trailing line is skipped, and an
empty/meta-less file exits with a clear message instead of a traceback.

``--serve`` reads a resident-fleet SERVICE stream (serve/service.py,
``LIBRABFT_SERVE_OUT`` / ``FleetService(out=...)``): the admission-queue
view — pending/admitted/egressed counts, slot occupancy, and per-request
ttfc (admission → first polled chunk) as requests flow through, plus the
digest heartbeat.  Same hardening as every other mode: an empty, foreign,
or meta-less file exits 1 with a message, never a traceback.

``--ledger`` reads a RUNTIME-LEDGER stream instead (telemetry/ledger.py,
``LIBRABFT_LEDGER_OUT``): per-chunk dispatch-enqueue vs blocking-poll
wall time for every recorded host loop, the measured pipeline-overlap
fraction of the double-buffered dispatch, dispatch-queue bubbles, the
time-to-first-chunk headline, and the compile ledger (per structural
key, with persistent-cache hit/miss).

``--timeline`` treats the path as a glob over per-host RUNTIME-LEDGER
streams (a ``distributed.local_cluster(..., ledger=True)`` workdir's
``ledger-p<pid>.ndjson`` set) and exports ONE merged Perfetto/Chrome
trace: per-host clock offsets are estimated from the coordinator
handshake spans and every host's dispatch/poll spans land clock-aligned
on their own process track (telemetry/observatory.py).

One-shot views load through the observatory's unified ingest
(telemetry/observatory.py) — the schema'd store every stream kind lands
in — rather than per-kind private parsers.  No jax import anywhere: the
viewer is pure host-side and starts instantly.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from librabft_simulator_tpu.telemetry import ledger as tledger  # noqa: E402
from librabft_simulator_tpu.telemetry import observatory as tobs  # noqa: E402
from librabft_simulator_tpu.telemetry import schema as tschema  # noqa: E402


def _flag_names(flags: int) -> str:
    names = [d for i, d in enumerate(tschema.WD_DETECTORS)
             if flags & (1 << i)]
    return ",".join(names) if names else "-"


class _View:
    """Stateful row formatter: meta/fleet lines adjust the header and the
    padding correction; row lines print one status line each."""

    def __init__(self, out=sys.stdout):
        self.out = out
        self.total = None     # padded instance count (digest's halted basis)
        self.padding = 0
        self.header_done = False

    def _header(self):
        print(f"{'chunk':>5} {'t_s':>8} {'halted':>12} {'events':>10} "
              f"{'ev/s':>10} {'commits':>8} {'drop':>6} {'ovfl':>6} "
              f"{'qmax':>5} {'rounds':>11} {'eta_s':>8}  WATCHDOG",
              file=self.out)
        self.header_done = True

    def feed(self, obj: dict) -> None:
        kind = obj.get("kind")
        if kind == "meta":
            tschema.require_registry_version(obj.get("registry_version"),
                                             what="stream")
            print(f"# fleet stream: n_nodes={obj.get('n_nodes')} "
                  f"watchdog={'on' if obj.get('watchdog') else 'off'} "
                  f"registry v{obj.get('registry_version')}", file=self.out)
            if obj.get("total_instances"):
                self.total = int(obj["total_instances"])
            return
        if kind == "fleet":
            self.total = int(obj["total_instances"])
            self.padding = int(obj.get("padding", 0))
            if self.padding:
                print(f"# fleet: {obj['n_valid']} instances "
                      f"(+{self.padding} pre-halted padding)", file=self.out)
            return
        if kind != "row":
            return
        if not self.header_done:
            self._header()
        halted = obj["halted"] - self.padding
        denom = (self.total - self.padding) if self.total else None
        halt = f"{halted}/{denom}" if denom else f"{halted}"
        rounds = f"{obj['committed_round_min']}..{obj['committed_round_max']}"
        eta = obj.get("eta_s")
        flags = obj.get("watchdog_flags", 0)
        line = (f"{obj['chunk']:>5} {obj['t_s']:>8.2f} {halt:>12} "
                f"{obj['events']:>10} {obj['ev_per_s']:>10.1f} "
                f"{obj['commits']:>8} {obj['drops']:>6} {obj['overflow']:>6} "
                f"{obj['queue_depth_max']:>5} {rounds:>11} "
                f"{eta if eta is not None else '-':>8}  "
                f"{_flag_names(flags)}")
        print(line, file=self.out, flush=True)


def follow(path: str, view: _View, poll_s: float = 0.5,
           idle_timeout_s: float | None = None) -> None:
    """Tail the NDJSON file live: feed every complete line as it lands,
    keep waiting for more (a run in progress appends between polls).
    Stops after ``idle_timeout_s`` with no new data (None = forever)."""
    idle = 0.0
    with open(path) as f:
        buf = ""
        while True:
            chunk = f.read()
            if chunk:
                idle = 0.0
                buf += chunk
                while "\n" in buf:
                    line, buf = buf.split("\n", 1)
                    if line.strip():
                        view.feed(json.loads(line))
            else:
                idle += poll_s
                if idle_timeout_s is not None and idle >= idle_timeout_s:
                    return
                time.sleep(poll_s)


def show_ledger(path: str, out=None) -> int:
    """The --ledger view: per-chunk dispatch/poll wall time for every
    recorded host loop, the measured overlap fraction + bubbles of the
    double-buffered dispatch, time_to_first_chunk, and the compile
    ledger (key, shapes, compile seconds, persistent-cache verdict)."""
    out = out if out is not None else sys.stdout  # late-bound: capturable
    meta, rows = tledger.load_ndjson(path)
    run_meta = {r["run"]: r for r in rows if r.get("kind") == "run"}
    runs = sorted(run_meta) or sorted(
        {r["run"] for r in rows
         if r.get("kind") == "span" and r.get("run") is not None})
    printed = False
    for rid in runs:
        pipe = tledger.pipeline_stats(rows, run=rid)
        if not pipe["chunks"]:
            continue
        printed = True
        rm = run_meta.get(rid, {})
        # Overlap is only meaningful for a double-buffered loop (the run
        # row says pipeline=True); a serial completion loop polls the
        # chunk it just dispatched, so its ~1.0 would be a lie.
        overlap = (pipe["overlap_fraction"] if rm.get("pipeline")
                   else "n/a (not double-buffered)")
        print(f"# run {rid} ({rm.get('label', '?')}): "
              f"chunks={pipe['chunks']} "
              f"overlap={overlap} "
              f"bubbles={pipe['bubble_count']} "
              f"time_to_first_chunk={pipe.get('time_to_first_chunk_s')}s",
              file=out)
        ring = tledger.ring_stats(rows, run=rid)
        if ring:
            # Device-dispatch ring loops (SimParams.wrap="device"): the
            # poll-amortization columns the ring exists for.
            print(f"# ring: dispatches={ring['dispatches']} "
                  f"retired_chunks={ring['retired_chunks']} "
                  f"retired_per_dispatch={ring['retired_per_dispatch']} "
                  f"polls_per_retired_chunk="
                  f"{ring['polls_per_retired_chunk']} "
                  f"ring_full={ring['ring_full']} "
                  f"early_exit={ring['early_exit']}", file=out)
        print(f"{'chunk':>5} {'dispatch_ms':>12} {'poll_ms':>9}  note",
              file=out)
        for row in pipe["rows"]:
            note = "bubble" if row["chunk"] in pipe["bubbles"] else (
                "cold (compile)" if row["chunk"] == 0 else "")
            print(f"{row['chunk']:>5} {row['dispatch_s'] * 1e3:>12.2f} "
                  f"{row['poll_s'] * 1e3:>9.2f}  {note}", file=out)
    compiles = [r for r in rows if r.get("kind") == "compile"]
    if compiles:
        printed = True
        aot_hits = sum(1 for e in compiles if e.get("cache") == "aot-hit")
        aot_stale = sum(1 for e in compiles if e.get("cache") == "aot-stale")
        print(f"# compile ledger: {len(compiles)} builds"
              + (f" ({aot_hits} aot-hit)" if aot_hits else "")
              + (f" ({aot_stale} AOT-STALE — rebuild the store: "
                 f"scripts/warm_cache.py)" if aot_stale else ""), file=out)
        for e in compiles:
            # aot-hit entries paid deserialize seconds, not a compile;
            # aot-stale entries name the fallback verdict they fell to.
            if e.get("cache") == "aot-hit":
                cost = f"aot_load_s={e.get('aot_load_s', 0):.2f}"
            else:
                cost = f"compile_s={e.get('compile_s', 0):.2f}"
            verdict = e.get("cache")
            if e.get("fallback"):
                verdict = f"{verdict}->{e['fallback']}"
            print(f"  {e.get('key')} {e.get('engine', '?'):>14} "
                  f"shapes={e.get('shapes')} {verdict} {cost} "
                  f"first_call_s={e.get('first_call_s', 0):.2f}", file=out)
    if not printed:
        print("no ledger rows yet", file=sys.stderr)
        return 1
    return 0


class _ServeView:
    """The --serve formatter: request-lifecycle rows as an event log,
    digest rows as a compact occupancy heartbeat."""

    def __init__(self, out=sys.stdout):
        self.out = out
        self.slots = None
        self.last: dict = {}
        self.header_done = False

    def _header(self):
        print(f"{'t_s':>8} {'event':>11} {'request':>10} {'slot':>5} "
              f"{'ttfc_s':>8} {'pend':>5} {'actv':>5} {'done':>5}  detail",
              file=self.out)
        self.header_done = True

    def feed(self, obj: dict) -> None:
        kind = obj.get("kind")
        if kind == "meta":
            tschema.require_registry_version(obj.get("registry_version"),
                                             what="serve stream")
            if not obj.get("serve"):
                raise ValueError(
                    "not a serve stream (no serve marker in the meta "
                    "line); plain digest streams want the default view")
            self.slots = obj.get("slots")
            print(f"# resident fleet: {self.slots} slots x "
                  f"chunk {obj.get('chunk')} (n_nodes={obj.get('n_nodes')},"
                  f" registry v{obj.get('registry_version')})",
                  file=self.out)
            return
        if kind == "request":
            if not self.header_done:
                self._header()
            self.last = obj
            ttfc = obj.get("ttfc_s")
            detail = ""
            if obj.get("event") == "egressed":
                res = obj.get("result") or {}
                detail = (f"events={res.get('events')} "
                          f"commits={res.get('commits')} "
                          f"safe={res.get('safe')} "
                          f"latency_s={obj.get('latency_s')}")
                wd = res.get("watchdog")
                if wd:
                    # Per-request watchdog referee (serve/_result_of):
                    # in-graph trip counts = the safety/liveness verdict
                    # for this admitted (possibly adversarial) scenario.
                    trips = ",".join(
                        f"{k}={v}" for k, v in wd.items()
                        if k not in ("safety_ok", "liveness_ok") and v)
                    detail += (f" wd[safety={'OK' if wd.get('safety_ok') else 'TRIPPED'}"
                               f" liveness={'OK' if wd.get('liveness_ok') else 'STALLED'}"
                               + (f" {trips}" if trips else "") + "]")
            print(f"{obj.get('t_s', 0):>8.2f} {obj.get('event', '?'):>11} "
                  f"{str(obj.get('id')):>10} "
                  f"{str(obj.get('slot', '-')):>5} "
                  f"{ttfc if ttfc is not None else '-':>8} "
                  f"{obj.get('pending', 0):>5} {obj.get('active', 0):>5} "
                  f"{obj.get('egressed', 0):>5}  {detail}",
                  file=self.out, flush=True)
            return
        if kind == "row":
            if not self.header_done:
                self._header()
            occ = (f"occupancy {self.last.get('active', '?')}/{self.slots}"
                   if self.slots else "")
            print(f"{obj.get('t_s', 0):>8.2f} {'chunk':>11} "
                  f"{'':>10} {'':>5} {'':>8} "
                  f"{self.last.get('pending', 0):>5} "
                  f"{self.last.get('active', 0):>5} "
                  f"{self.last.get('egressed', 0):>5}  "
                  f"halted={obj.get('halted')} events={obj.get('events')} "
                  f"{occ}", file=self.out, flush=True)


def show_serve(path: str, out=None) -> int:
    """The --serve one-shot view (exit 1 on empty/foreign files)."""
    out = out if out is not None else sys.stdout
    obs = tobs.from_paths([path])
    meta = obs.sources[0]["meta"]
    view = _ServeView(out=out)
    view.feed(dict(meta, kind="meta"))
    events = obs.select(kind="request")
    if not events:
        print("no request rows yet", file=sys.stderr)
        return 1
    for r in events:
        view.feed(r)
    # Closing occupancy summary from the newest row.
    last = events[-1]
    print(f"# pending={last.get('pending')} active={last.get('active')} "
          f"egressed={last.get('egressed')} of {meta.get('slots')} slots",
          file=out)
    # Watchdog referee roll-up: per-request safety/liveness verdicts over
    # every egressed scenario that carried the [WD] trip counters.
    verdicts = [e["result"]["watchdog"] for e in events
                if e.get("event") == "egressed"
                and (e.get("result") or {}).get("watchdog")]
    if verdicts:
        bad_safe = sum(1 for w in verdicts if not w.get("safety_ok"))
        stalled = sum(1 for w in verdicts if not w.get("liveness_ok"))
        print(f"# watchdog: {len(verdicts)} refereed requests, "
              f"{bad_safe} safety-tripped, {stalled} liveness-stalled",
              file=out)
    return 0


class _MergeView:
    """The --merge formatter: digest/request rows from MULTIPLE per-host
    streams as one fleet view, each row tagged with its writer host."""

    def __init__(self, out=sys.stdout):
        self.out = out
        self.header_done = False

    def _header(self):
        print(f"{'host':>5} {'chunk':>5} {'t_s':>8} {'halted':>8} "
              f"{'events':>10} {'ev/s':>10} {'commits':>8} {'drop':>6} "
              f"{'rounds':>11}  WATCHDOG/EVENT", file=self.out)
        self.header_done = True

    def feed(self, obj: dict, host: str) -> None:
        kind = obj.get("kind")
        if kind == "meta":
            tschema.require_registry_version(obj.get("registry_version"),
                                             what=f"stream (host {host})")
            print(f"# host {host}: n_nodes={obj.get('n_nodes')} "
                  f"process {obj.get('process_id', '?')}/"
                  f"{obj.get('process_count', '?')} "
                  f"registry v{obj.get('registry_version')}", file=self.out)
            return
        if not self.header_done:
            self._header()
        if kind == "row":
            rounds = (f"{obj['committed_round_min']}.."
                      f"{obj['committed_round_max']}")
            print(f"{host:>5} {obj['chunk']:>5} {obj['t_s']:>8.2f} "
                  f"{obj['halted']:>8} {obj['events']:>10} "
                  f"{obj['ev_per_s']:>10.1f} {obj['commits']:>8} "
                  f"{obj['drops']:>6} {rounds:>11}  "
                  f"{_flag_names(obj.get('watchdog_flags', 0))}",
                  file=self.out, flush=True)
        elif kind == "request":
            print(f"{host:>5} {'':>5} {obj.get('t_s', 0):>8.2f} "
                  f"{'':>8} {'':>10} {'':>10} {'':>8} {'':>6} {'':>11}  "
                  f"request {obj.get('id')} {obj.get('event')}",
                  file=self.out, flush=True)


def _merge_paths(pattern: str) -> list[str]:
    import glob as _glob

    paths = sorted(_glob.glob(pattern))
    if not paths:
        raise ValueError(
            f"--merge {pattern!r} matched no files (per-host streams are "
            "named <base>.p<pid>.ndjson — distributed.egress."
            "host_stream_path)")
    return paths


def _host_label(path: str, meta: dict) -> str:
    pid = meta.get("process_id")
    return f"p{pid}" if pid is not None else os.path.basename(path)


def show_merge(pattern: str, summary: bool = False, out=None) -> int:
    """The --merge one-shot view: every matched per-host stream decoded
    into one observatory store, rows interleaved by wall time, host tag
    per row.  --summary prints one final-digest JSON per host instead
    (the digests are mesh-reduced in-graph, so every host's final row
    reports the whole fleet — the per-host tags are the cross-check)."""
    out = out if out is not None else sys.stdout
    obs = tobs.from_paths(_merge_paths(pattern))
    if summary:
        doc = {}
        for src in obs.sources:
            host = _host_label(src["path"], src["meta"])
            data = obs.select(kind="row", host=src["host"])
            last = data[-1] if data else None
            doc[host] = (
                None if last is None else
                {"chunks": len(data), "elapsed_s": last["t_s"],
                 "final": {n: last[n] for n, _ in tschema.DIGEST_SLOTS}})
        print(json.dumps(doc, indent=1), file=out)
        return 0
    view = _MergeView(out=out)
    labels = {src["path"]: _host_label(src["path"], src["meta"])
              for src in obs.sources}
    for src in obs.sources:
        view.feed(dict(src["meta"], kind="meta"), labels[src["path"]])
    tagged = [(e.get("t_s", 0), labels[e["_path"]], e) for e in obs.events]
    for _, host, r in sorted(tagged, key=lambda t: (t[0], t[1])):
        view.feed(r, host)
    return 0


def show_timeline(pattern: str, out_path: str, out=None) -> int:
    """The --timeline export: ingest every matched per-host LEDGER
    stream (distributed/local_cluster names them ledger-p<pid>.ndjson),
    estimate per-host clock offsets from the coordinator handshake
    spans, and write ONE merged Chrome-trace/Perfetto JSON — every
    host's dispatch/poll spans on its own process track, clock-aligned
    (telemetry/observatory.py).  Exits 1 on zero matches or a span-less
    ingest."""
    out = out if out is not None else sys.stdout
    obs = tobs.Observatory()
    obs.ingest_glob(pattern)
    ledgers = [s for s in obs.sources if s["stream"] == tobs.LEDGER]
    if not ledgers:
        raise ValueError(
            f"--timeline {pattern!r} matched no runtime-ledger streams "
            "(point it at LIBRABFT_LEDGER_OUT files, e.g. the "
            "ledger-p*.ndjson set a distributed.local_cluster(..., "
            "ledger=True) workdir holds)")
    doc = obs.merged_perfetto(out_path)
    spans = sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")
    if not spans:
        print("no ledger spans yet", file=sys.stderr)
        return 1
    offs = doc["otherData"]["clock_offsets_s"]
    print(f"# merged timeline: {len(ledgers)} host ledger(s), "
          f"{spans} spans -> {out_path}", file=out)
    for h in sorted(offs):
        print(f"#   host {h}: clock offset {offs[h]:+.6f}s", file=out)
    return 0


def follow_merge(pattern: str, view: _MergeView, poll_s: float = 0.5,
                 idle_timeout_s: float | None = None) -> None:
    """Tail every matched per-host stream live, tagging rows as they
    land (arrival order across hosts; the per-row t_s orders exactly).
    The glob is re-evaluated between polls: pod hosts open their streams
    at staggered times, and a file appearing AFTER the watcher started
    joins the merged view from its first line."""
    import glob as _glob

    _merge_paths(pattern)  # zero matches at start: loud exit-1 contract
    files: dict = {}       # path -> (fh, host label, line buffer)
    idle = 0.0
    try:
        while True:
            for path in sorted(_glob.glob(pattern)):
                if path not in files:
                    files[path] = [open(path), os.path.basename(path), ""]
            got = False
            for path, slot in files.items():
                f, _, _ = slot
                chunk = f.read()
                if not chunk:
                    continue
                got = True
                slot[2] += chunk
                while "\n" in slot[2]:
                    line, slot[2] = slot[2].split("\n", 1)
                    if not line.strip():
                        continue
                    obj = json.loads(line)
                    if obj.get("kind") == "meta":
                        slot[1] = _host_label(path, obj)
                    view.feed(obj, slot[1])
            if got:
                idle = 0.0
            else:
                idle += poll_s
                if idle_timeout_s is not None and idle >= idle_timeout_s:
                    return
                time.sleep(poll_s)
    finally:
        for slot in files.values():
            slot[0].close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("path", help="NDJSON stream file (TimelineRecorder out=)")
    ap.add_argument("--once", action="store_true",
                    help="print what's in the file now and exit")
    ap.add_argument("--summary", action="store_true",
                    help="print only the final digest as JSON and exit")
    ap.add_argument("--ledger", action="store_true",
                    help="the file is a runtime-ledger stream "
                         "(LIBRABFT_LEDGER_OUT): print per-chunk "
                         "dispatch/poll timing, overlap, bubbles, and "
                         "the compile ledger")
    ap.add_argument("--serve", action="store_true",
                    help="the file is a resident-fleet service stream "
                         "(serve/; LIBRABFT_SERVE_OUT): print the "
                         "admission-queue event log — pending/admitted/"
                         "egressed counts, slot occupancy, per-request "
                         "ttfc — plus the digest heartbeat; --once/"
                         "default follow both work")
    ap.add_argument("--merge", action="store_true",
                    help="the path is a GLOB over per-host streams "
                         "(<base>.p<pid>.ndjson, distributed/egress.py): "
                         "follow/summarize them as one fleet view with a "
                         "host tag per row; exits 1 on zero matches")
    ap.add_argument("--timeline", action="store_true",
                    help="the path is a GLOB over per-host runtime-ledger "
                         "streams (ledger-p<pid>.ndjson): export ONE "
                         "merged clock-aligned Perfetto trace to --out "
                         "(telemetry/observatory.py cross-host merge)")
    ap.add_argument("--out", default="fleet_timeline.json",
                    help="--timeline output path (Chrome-trace JSON, "
                         "loadable in ui.perfetto.dev)")
    ap.add_argument("--poll", type=float, default=0.5,
                    help="follow-mode poll interval in seconds")
    ap.add_argument("--idle-timeout", type=float, default=None,
                    help="stop following after this many idle seconds")
    args = ap.parse_args(argv)

    try:
        if args.timeline:
            return show_timeline(args.path, args.out)

        if args.merge:
            if args.once or args.summary:
                return show_merge(args.path, summary=args.summary)
            follow_merge(args.path, _MergeView(), poll_s=args.poll,
                         idle_timeout_s=args.idle_timeout)
            return 0

        if args.ledger:
            return show_ledger(args.path)

        if args.serve:
            if args.once or args.summary:
                return show_serve(args.path)
            view = _ServeView()
            follow(args.path, view, poll_s=args.poll,
                   idle_timeout_s=args.idle_timeout)
            return 0

        if args.summary:
            obs = tobs.from_paths([args.path])
            data = obs.select(kind="row")
            if not data:
                print("no rows yet", file=sys.stderr)
                return 1
            last = data[-1]
            print(json.dumps({
                "chunks": len(data), "elapsed_s": last["t_s"],
                "final": {n: last[n] for n, _ in tschema.DIGEST_SLOTS},
                "watchdog_flags": last["watchdog_flags"],
                "watchdog": _flag_names(last["watchdog_flags"]),
            }, indent=1))
            return 0

        view = _View()
        if args.once:
            obs = tobs.from_paths([args.path])
            view.feed(dict(obs.sources[0]["meta"], kind="meta"))
            for r in obs.events:
                view.feed(r)
            return 0
        follow(args.path, view, poll_s=args.poll,
               idle_timeout_s=args.idle_timeout)
    except (OSError, ValueError) as e:
        # An empty, still-initializing, or foreign file is an operator
        # answer ("nothing to show yet / wrong file"), not a traceback.
        print(f"fleet_watch: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
