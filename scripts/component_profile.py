"""Component-level wall-time attribution for the serial step.

Times each piece of the per-event machinery as its own jitted, vmapped
executable over a [B] batch of node slices taken from a warmed-up fleet —
identical inputs per component, no trajectory feedback, so the numbers are
directly comparable (unlike the ABLATE= stubs, which perturb trajectories).

Run: JAX_PLATFORMS=cpu python scripts/component_profile.py
"""
import os
import time

# CPU by default; JAX_PLATFORMS=axon profiles the chip (jax reads the env
# var itself — no config.update needed).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from librabft_simulator_tpu.utils.cache import setup_compile_cache  # noqa: E402

setup_compile_cache()

import jax.numpy as jnp
import numpy as np

from librabft_simulator_tpu.core import data_sync, node as node_ops
from librabft_simulator_tpu.core.types import (
    Payload, SimParams, pack_payload, unpack_payload)
from librabft_simulator_tpu.sim import simulator as S


def main():
    n = int(os.environ.get("PN", "4"))
    B = int(os.environ.get("PB", "2048"))
    reps = int(os.environ.get("PREPS", "20"))
    p = SimParams(n_nodes=n, delay_kind="uniform", max_clock=2**30,
                  queue_cap=max(32, 4 * n),
                  epoch_handoff=os.environ.get("PHO", "0") == "1")
    seeds = np.arange(B, dtype=np.uint32)
    st = S.init_batch(p, seeds)
    st = S.dedupe_buffers(st)
    run = S.make_run_fn(p, 512)
    st = run(st)  # steady state
    jax.block_until_ready(st)

    # One node slice per instance (node 0) + a round-robin incoming payload
    # (re-broadcast each instance's own queue slot 0 payload).
    a = jnp.zeros((B,), jnp.int32)
    s_a = jax.tree.map(lambda x: x[:, 0], st.store)
    pm_a = jax.tree.map(lambda x: x[:, 0], st.pm)
    nx_a = jax.tree.map(lambda x: x[:, 0], st.node)
    cx_a = jax.tree.map(lambda x: x[:, 0], st.ctx)
    pay_rows = st.queue.payload[:, 0]
    weights = st.weights
    clock = st.clock
    dur = jnp.asarray(p.duration_table())

    def timed(name, fn, *args):
        f = jax.jit(fn)
        out = f(*args)
        jax.block_until_ready(out)  # compile
        t0 = time.perf_counter()
        for _ in range(reps):
            out = f(*args)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / reps
        print(f"{name:28s} {dt*1e3:9.2f} ms/call  ({dt/B*1e6:7.2f} us/event)")
        return dt

    def step_full(st):
        return S.step(p, jnp.asarray(p.delay_table()), dur, st)

    timed("FULL step", jax.vmap(step_full), st)

    unpack = lambda rows: jax.vmap(lambda r: unpack_payload(p, r))(rows)  # noqa
    pay = unpack(pay_rows)

    timed("unpack_payload", unpack, pay_rows)
    timed("handle_notification",
          jax.vmap(lambda s, w, q: data_sync.handle_notification(p, s, w, q)),
          s_a, weights, pay)
    timed("handle_response",
          jax.vmap(lambda s, nx, cx, w, q: data_sync.handle_response(
              p, s, nx, cx, w, q)), s_a, nx_a, cx_a, weights, pay)
    timed("update_node",
          jax.vmap(lambda s, pm, nx, cx, w, aa, c: node_ops.update_node(
              p, s, pm, nx, cx, w, aa, c, dur)),
          s_a, pm_a, nx_a, cx_a, weights, a, clock)
    timed("create_notification",
          jax.vmap(lambda s, aa: data_sync.create_notification(p, s, aa)),
          s_a, a)
    timed("handle_request(resp build)",
          jax.vmap(lambda s, aa, q: data_sync.handle_request(p, s, aa, q)),
          s_a, a, pay)
    timed("create_request",
          jax.vmap(lambda s: data_sync.create_request(p, s)), s_a)
    def pack4(q):
        # Four DISTINCT payloads (perturb one field per copy) — a stack of
        # one traced pack would fold into a single computation and
        # under-attribute packing ~4x.
        return jnp.stack([
            pack_payload(q.replace(epoch=q.epoch + i)) for i in range(4)])

    timed("pack_payload x4", jax.vmap(pack4), pay)
    timed("timeout_batch x2",
          jax.vmap(lambda s, w, q: data_sync._insert_timeout_batch(
              p, data_sync._insert_timeout_batch(p, s, w, q.tc_to, q.epoch),
              w, q.cur_to, q.epoch)), s_a, weights, pay)

    def slice_roundtrip(st):
        aa = st.clock % p.n_nodes  # data-dependent index like the real step
        parts = (st.store, st.pm, st.node, st.ctx)
        sl = [S._node_slice(x, aa) for x in parts]
        upd = [S._node_update(x, aa, v) for x, v in zip(parts, sl)]
        return st.replace(store=upd[0], pm=upd[1], node=upd[2], ctx=upd[3])

    timed("node slice+update (4 structs)", jax.vmap(slice_roundtrip), st)
    timed("_select_event",
          jax.vmap(lambda s: S._select_event(p, s)), st)

    def queue_scatter(st):
        q = st.queue
        tgt = jnp.arange(2 * p.n_nodes + 1, dtype=jnp.int32) % p.queue_cap
        rows = jnp.broadcast_to(q.payload[0], (2 * p.n_nodes + 1,
                                               q.payload.shape[1]))
        return q.replace(
            valid=q.valid.at[tgt].set(True),
            time=q.time.at[tgt].set(1), kind=q.kind.at[tgt].set(1),
            stamp=q.stamp.at[tgt].set(1), sender=q.sender.at[tgt].set(1),
            receiver=q.receiver.at[tgt].set(1),
            payload=q.payload.at[tgt].set(rows))

    timed("queue scatter block", jax.vmap(queue_scatter), st)

    from librabft_simulator_tpu.core import store as store_ops
    timed("insert_qc x2",
          jax.vmap(lambda s, w, q: store_ops.insert_qc(
              p, store_ops.insert_qc(p, s, w, q.hcc)[0], w, q.hqc)),
          s_a, weights, pay)
    timed("insert_block+vote",
          jax.vmap(lambda s, w, q: store_ops.insert_vote(
              p, store_ops.insert_block(p, s, w, q.prop_blk, q.epoch)[0],
              w, q.vote)), s_a, weights, pay)


if __name__ == "__main__":
    main()
