"""NDJSON front-end for the resident fleet service (serve/).

Reads scenario requests from an NDJSON file (one JSON object per line —
see serve/api.py for the schema), serves them on a resident fleet, and
writes per-request results as NDJSON.  The live digest + request stream
(``--stream`` / ``LIBRABFT_SERVE_OUT``) is followable from another
terminal with ``scripts/fleet_watch.py --serve``.

Usage:
    python scripts/fleet_serve.py requests.ndjson
    python scripts/fleet_serve.py requests.ndjson --out results.ndjson \\
        --slots 8 --chunk 64 --dp 2 --stream /tmp/serve.ndjson
    python scripts/fleet_serve.py requests.ndjson --nodes 4 --telemetry

Service shape knobs (``--slots``/``--chunk`` default from
``LIBRABFT_SERVE_SLOTS``/``LIBRABFT_SERVE_CHUNK``): the fleet's slot count
and macro-chunk length are the residency geometry; per-request scenario
knobs ride the requests themselves.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("requests", help="NDJSON request file")
    ap.add_argument("--out", default=None,
                    help="results NDJSON path (default: stdout)")
    ap.add_argument("--slots", type=int, default=None,
                    help="fleet slots (default LIBRABFT_SERVE_SLOTS or 8)")
    ap.add_argument("--chunk", type=int, default=None,
                    help="macro-steps per dispatched chunk "
                         "(default LIBRABFT_SERVE_CHUNK or 64)")
    ap.add_argument("--dp", type=int, default=1,
                    help="dp mesh width (devices; default 1)")
    ap.add_argument("--nodes", type=int, default=4,
                    help="committee size every scenario shares (structural)")
    ap.add_argument("--telemetry", action="store_true",
                    help="arm the in-graph telemetry plane (per-request "
                         "metrics ride the egress results)")
    ap.add_argument("--watchdog", action="store_true",
                    help="arm the in-graph consensus watchdog (trip counts "
                         "ride the streamed digests)")
    ap.add_argument("--stream", default=None,
                    help="live digest+request NDJSON stream path "
                         "(default LIBRABFT_SERVE_OUT; follow with "
                         "fleet_watch --serve)")
    ap.add_argument("--checkpoint", default=None,
                    help="preempt after draining: checkpoint the resident "
                         "state here (resume with FleetService.resume)")
    ap.add_argument("--max-chunks", type=int, default=None,
                    help="chunk ceiling for the serve loop")
    args = ap.parse_args(argv)

    if args.dp > 1:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                f"{max(8, args.dp)}").strip()

    import jax

    from librabft_simulator_tpu.core.types import SimParams
    from librabft_simulator_tpu.parallel import mesh as mesh_ops
    from librabft_simulator_tpu.serve import FleetService, load_requests
    from librabft_simulator_tpu.utils.cache import setup_compile_cache

    setup_compile_cache()

    try:
        requests = load_requests(args.requests)
    except (OSError, ValueError) as e:
        print(f"fleet_serve: {e}", file=sys.stderr)
        return 1

    p = SimParams(n_nodes=args.nodes, telemetry=args.telemetry,
                  watchdog=args.watchdog)
    mesh = (mesh_ops.make_mesh(n_dp=args.dp, n_mp=1,
                               devices=jax.devices()[:args.dp])
            if args.dp > 1 else None)
    out_f = open(args.out, "w") if args.out else sys.stdout
    try:
        with FleetService(p, slots=args.slots, chunk=args.chunk, mesh=mesh,
                          out=args.stream) as svc:
            for rid, spec in requests:
                svc.submit(spec, request_id=rid)
            kw = ({} if args.max_chunks is None
                  else {"max_chunks": args.max_chunks})
            results = svc.drain(**kw)
            for rid, _ in requests:  # submission order, not egress order
                out_f.write(json.dumps(results[rid]) + "\n")
                out_f.flush()  # per-row: a timeout kill keeps landed rows
            occ = svc.fleet.occupancy()
            print(f"# served {len(results)} requests on {occ['slots']} "
                  f"slots, {svc.fleet.chunks_polled} chunks",
                  file=sys.stderr)
            if args.checkpoint:
                svc.preempt(args.checkpoint)
                print(f"# resident state checkpointed to "
                      f"{args.checkpoint} (+.serve.json)", file=sys.stderr)
    finally:
        if args.out:
            out_f.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
