"""Build the AOT executable store (and warm the compile caches) for the
test suite: THE build step of the compile-tax pipeline.

Each heavy (engine, shape) pair compiles in its own subprocess (a single
long-lived process accumulating many large compiles risks the jaxlib
serialize segfault), and — by default — each child runs with
``LIBRABFT_AOT_WRITE=1``: every chunk executable it builds is exported
into the AOT store (utils/aot.py, ``LIBRABFT_AOT_DIR``) as a serialized
ready-to-load artifact with a manifest entry.  CI and fleet start then
LOAD those executables (an ``aot-hit`` pays deserialize seconds, not
trace+lower+XLA-compile), which is what turns the 42 s cold fleet start
into seconds and the tier-1 cold-dot gap into the warm count.

The export compile deliberately bypasses the persistent XLA compile
cache (a cache-hydrated executable re-serializes broken — see
utils/aot._export), so with AOT on this script warms the AOT STORE; run
it with ``LIBRABFT_AOT=0`` to get the old persistent-cache-only warming
behavior.

Usage:  python scripts/warm_cache.py            # suite shapes (incl. sharded)
        python scripts/warm_cache.py --bench    # bench + 5-config sweep shapes
        python scripts/warm_cache.py --fleet    # BENCH_FLEET dp-ladder rungs
        python scripts/warm_cache.py --macro    # BENCH_MACRO K-ladder rungs
        python scripts/warm_cache.py --from-ledger PATH  # every chunk
                                                # executable a previous run's
                                                # streamed runtime ledger
                                                # records (data-driven matrix)
        python scripts/warm_cache.py --list     # show shapes

``--bench`` drives bench.py itself (one child per config, BENCH_REPS=1) so
the compiled (structural shape, scan length, batch) keys match the real
sweep exactly; afterwards ``BENCH_SWEEP=1 python bench.py`` runs from the
persistent cache with ~0 s compile per config.  Run it in CI / before a
graded window so measurement time is spent measuring, not compiling.
"""
import os
import subprocess
import sys

SHAPES = [
    # (engine, SimParams kwargs, batch, chunk) — representative heavy shapes
    # from the suite.  Batch size AND scan length are part of the compiled
    # shape: batch=None means an UNBATCHED single-instance run (how the
    # parity tests drive the serial engine); the parallel entries mirror
    # tests/test_parallel_sim.py small_params batches (chunk 256) and
    # tests/test_epoch_handoff.py boundary_params; the last entry matches
    # test_multichip's sharded-parallel chunk=64.
    ("serial", {}, None, 256),                            # parity default
    ("serial", {"n_nodes": 4}, None, 256),
    ("serial", {"n_nodes": 3, "commands_per_epoch": 6}, None, 256),  # handoff
    ("parallel",
     {"n_nodes": 4, "delay_kind": "uniform", "window": 8, "chain_k": 2,
      "commit_log": 16}, 6, 256),
    ("parallel",
     {"n_nodes": 4, "delay_kind": "uniform", "window": 8, "chain_k": 2,
      "commit_log": 16}, 8, 256),
    ("parallel",
     {"n_nodes": 3, "commands_per_epoch": 6, "delay_kind": "uniform",
      "drop_prob": 0.1, "window": 16, "chain_k": 4}, 8, 256),
    ("parallel",
     {"n_nodes": 4, "delay_kind": "uniform", "window": 8, "chain_k": 2,
      "commit_log": 16}, 16, 64),  # test_multichip sharded-parallel shape
]

# The tier-1 micro fleet shapes, shared with tests/test_multichip.py via
# the pure-data module tests/fleet_shapes.py so the warmed executables and
# the suite's compiled shapes can never drift apart.
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))  # package root (aot manifest read)
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "tests"))
from fleet_shapes import (  # noqa: E402
    FLEET_ADV_LANE_KW, FLEET_ADV_SER_KW, FLEET_ADV_SERVE_KW, FLEET_B,
    FLEET_CHUNK, FLEET_LANE_KW, FLEET_MACRO_SER_KW, FLEET_MACRO_WD_SER_KW,
    FLEET_RING_LANE_KW, FLEET_RING_SER_KW, FLEET_SCENARIO_LANE_KW,
    FLEET_SCENARIO_SER_KW, FLEET_SER_KW, FLEET_WD_LANE_KW, FLEET_WD_SER_KW,
    SERVE_CHUNK, SERVE_DP, SERVE_SLOTS)

# Unsharded reference runs of the tier-1 2-shard parity pair, plus the
# watchdog-armed twins tests/test_stream.py runs (watchdog and its stall
# threshold are compile keys, so these are distinct executables).  For
# watchdog shapes the child also compiles the digest flavor
# (make_run_fn(..., digest=True)) — the [D]-vector poll contract
# run_to_completion(stream=...) drives is its own executable.
SHAPES += [
    ("serial", FLEET_SER_KW, FLEET_B, FLEET_CHUNK),
    ("parallel", FLEET_LANE_KW, FLEET_B, FLEET_CHUNK),
    ("serial", FLEET_WD_SER_KW, FLEET_B, FLEET_CHUNK),
    ("parallel", FLEET_WD_LANE_KW, FLEET_B, FLEET_CHUNK),
    # tests/test_stream.py's queue-saturation pin: the 4-node shape on the
    # SERIAL (shared-queue) engine, watchdog armed.
    ("serial", FLEET_WD_LANE_KW, FLEET_B, FLEET_CHUNK),
    # K-event macro-step rungs (SimParams.macro_k — a compile key: the
    # inner-scan trip count is baked in).  The plain macro chunk feeds
    # tests/test_checkpoint.py's macro-boundary round trip; the
    # watchdog-armed twin feeds tests/test_stream.py's K>1 digest pins
    # (its digest flavor compiles via the watchdog branch below).
    ("serial", FLEET_MACRO_SER_KW, FLEET_B, FLEET_CHUNK),
    ("serial", FLEET_MACRO_WD_SER_KW, FLEET_B, FLEET_CHUNK),
    # Resident-service scenario twins (serve/; tests/test_serve.py): the
    # per-slot scenario plane is a compile key, but the LAST one its
    # family needs — ONE serial entry covers every delay kind, drop rate,
    # Byzantine schedule, and 2-vs-3 commit chain the heterogeneous-fleet
    # referees mix (and the dedicated static chain-3 references of those
    # referees are the FLEET_SER_KW entries above).  The lane twin covers
    # the lane-engine scenario parity leg.
    ("serial", FLEET_SCENARIO_SER_KW, SERVE_SLOTS, SERVE_CHUNK),
    ("parallel", FLEET_SCENARIO_LANE_KW, SERVE_SLOTS, SERVE_CHUNK),
    # Adversary-engine twins (adversary/; tests/test_adversary.py): the
    # attack-schedule + network planes are a compile key (the adv_*
    # leaf shapes), but — like the scenario plane — the LAST fork their
    # family needs: one entry per engine serves every attack program,
    # link matrix, and partition schedule the referees sweep.  The bare
    # serial 4-node shape is their OFF twin (the inert/static-mask
    # identity references run the serial engine at FLEET_LANE_KW).
    ("serial", FLEET_LANE_KW, None, FLEET_CHUNK),
    ("serial", FLEET_ADV_SER_KW, None, FLEET_CHUNK),
    ("parallel", FLEET_ADV_LANE_KW, None, FLEET_CHUNK),
]

# Sanitizer (audit/sanitize.py) twins of the micro fleet pair: the
# checkify-instrumented chunk is its OWN executable (error plumbing wraps
# the whole scan), and tests/test_audit.py smokes it in tier-1 at exactly
# these shapes — warm or pay a cold compile inside the 870 s budget.  The
# graph-audit traces themselves (scripts/graph_audit.py) never compile,
# so they need no warming.
SANITIZE_SHAPES = [
    ("serial", FLEET_SER_KW, FLEET_B, FLEET_CHUNK),
    ("parallel", FLEET_LANE_KW, FLEET_B, FLEET_CHUNK),
    # The scenario-plane sanitizer build (round 16): LIBRABFT_CHECKIFY
    # on a SimParams.scenario=True fleet is its own executable (the
    # traced sc_delay reads + commit select under the checkify error
    # plumbing); tests/test_audit.py pins it bit-identical to the
    # unchecked scenario engine at exactly this shape.
    ("serial", FLEET_SCENARIO_SER_KW, FLEET_B, FLEET_CHUNK),
]

# (engine, SimParams kwargs, batch, chunk, dp): the sharded twins —
# run_sharded pads batch to the mesh size, so warming with the same raw
# batch reproduces the compiled shard shapes (which since the stream PR
# always carry the in-graph [D] digest on the poll path; the
# watchdog-armed shape is the digest-enabled micro fleet
# test_stream.py's sharded checks run).
SHARDED_SHAPES = [
    ("serial", FLEET_SER_KW, FLEET_B, FLEET_CHUNK, 2),
    ("parallel", FLEET_LANE_KW, FLEET_B, FLEET_CHUNK, 2),
    ("serial", FLEET_WD_SER_KW, FLEET_B, FLEET_CHUNK, 2),
    # The macro-armed sharded twin: test_stream.py pins the per-chunk
    # digest's true event accounting at K>1 through run_sharded.
    ("serial", FLEET_MACRO_WD_SER_KW, FLEET_B, FLEET_CHUNK, 2),
    # THE resident fleet service executable (serve/service.py builds the
    # identical make_sharded_run_fn key: scenario-armed structural params
    # + mesh + chunk): one entry serves every scenario config a serve
    # session admits — the executable-count collapse in one line.
    ("serial", FLEET_SCENARIO_SER_KW, SERVE_SLOTS, SERVE_CHUNK, SERVE_DP),
    # The adversarial resident-service executable (tests/test_adversary's
    # serve referee): scenario + adversary + watchdog armed — one sharded
    # entry admits every attack program as a request and referees it with
    # the in-graph watchdog trip counts.
    ("serial", FLEET_ADV_SERVE_KW, SERVE_SLOTS, SERVE_CHUNK, SERVE_DP),
    # Device-dispatch ring twins (SimParams.wrap="device"): the in-graph
    # chunk-retirement runner is its OWN executable family (AOT flavor
    # "ring"; ring depth in the key) — tests/test_multichip.py's ring
    # bit-identity referees and the perf sentinel's ring_dispatch rung
    # run exactly these shapes.
    ("serial", FLEET_RING_SER_KW, FLEET_B, FLEET_CHUNK, 2),
    ("parallel", FLEET_RING_LANE_KW, FLEET_B, FLEET_CHUNK, 2),
]

#: Shared child preamble: pin the CPU backend BEFORE the jax import and
#: force the tier-1 suite's device count (tests/conftest.py).  The
#: device count is load-bearing for the AOT store — store keys hash
#: jax.device_count(), so an export under any other count could never be
#: loaded by the suite (a permanent silent aot-miss).
CHILD_PREAMBLE = r"""
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
import jax
jax.config.update("jax_platforms", "cpu")
"""

CHILD = CHILD_PREAMBLE + r"""
import sys, json
import numpy as np
sys.path.insert(0, %(root)r)
from librabft_simulator_tpu.telemetry import ledger as tledger
from librabft_simulator_tpu.utils.cache import setup_compile_cache
setup_compile_cache()
from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.sim import parallel_sim, simulator
from librabft_simulator_tpu.sim.simulator import dedupe_buffers

engine_name, kw, batch, chunk = json.loads(sys.argv[1])
engine = parallel_sim if engine_name == "parallel" else simulator
p = SimParams(max_clock=500, **kw)
if batch is None:
    st = dedupe_buffers(engine.init_state(p, 0))
    run = engine.make_run_fn(p, chunk, batched=False)
else:
    st = dedupe_buffers(engine.init_batch(p, np.arange(batch, dtype=np.uint32)))
    run = engine.make_run_fn(p, chunk)
st = run(st)
if kw.get("watchdog") and batch is not None:
    # The [D]-digest poll flavor (telemetry/stream.py) is a distinct
    # executable; tests/test_stream.py drives it via
    # run_to_completion(stream=...).  The digest run donates its input,
    # so block on ITS outputs — the pre-donation reference is dead.
    st, _ = engine.make_run_fn(p, chunk, digest=True)(st)
jax.block_until_ready(st)
print("warmed", engine_name, kw, batch)
# The runtime ledger saw every build: say whether this shape actually
# warmed (persistent-miss = the compile this run exists to pre-pay) or
# was already warm — so a broken shared cache shows up HERE, not as a
# mystery tier-1 dot regression.
for e in tledger.get().compiles:
    print("  compile", e["key"], e["shapes"], e["cache"],
          "compile_s=%%.1f" %% e["compile_s"])
"""


SANITIZE_CHILD = CHILD_PREAMBLE + r"""
import sys, json
import numpy as np
sys.path.insert(0, %(root)r)
from librabft_simulator_tpu.utils.cache import setup_compile_cache
setup_compile_cache()
from librabft_simulator_tpu.audit import sanitize
from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.sim import parallel_sim, simulator

engine_name, kw, batch, chunk = json.loads(sys.argv[1])
engine = parallel_sim if engine_name == "parallel" else simulator
p = SimParams(max_clock=500, **kw)
st = engine.init_batch(p, np.arange(batch, dtype=np.uint32))
st = sanitize.run_checked(p, st, chunk, batched=True, engine=engine)
jax.block_until_ready(jax.tree_util.tree_leaves(st)[0])
print("warmed sanitize", engine_name, kw, batch)
"""


SHARDED_CHILD = CHILD_PREAMBLE + r"""
import sys, json
sys.path.insert(0, %(root)r)
from librabft_simulator_tpu.telemetry import ledger as tledger
from librabft_simulator_tpu.utils.cache import setup_compile_cache
setup_compile_cache()
from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.parallel import mesh as mesh_ops, sharded
from librabft_simulator_tpu.sim import parallel_sim, simulator

engine_name, kw, batch, chunk, dp = json.loads(sys.argv[1])
engine = parallel_sim if engine_name == "parallel" else simulator
p = SimParams(max_clock=500, **kw)
mesh = mesh_ops.make_mesh(n_dp=dp, n_mp=1, devices=jax.devices()[:dp])
st = engine.init_batch(p, sharded.fleet_seeds(0, batch))
st = sharded.run_sharded(p, mesh, st, num_steps=chunk, chunk=chunk,
                         engine=engine)
jax.block_until_ready(jax.tree_util.tree_leaves(st)[0])
print("warmed sharded", engine_name, kw, batch, "dp", dp)
for e in tledger.get().compiles:
    print("  compile", e["key"], e["shapes"], e["cache"],
          "compile_s=%%.1f" %% e["compile_s"])
"""


LEDGER_CHILD = CHILD_PREAMBLE + r"""
import sys, json, ast
import numpy as np
sys.path.insert(0, %(root)r)
from librabft_simulator_tpu.telemetry import ledger as tledger
from librabft_simulator_tpu.utils.cache import setup_compile_cache
setup_compile_cache()
from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.sim import parallel_sim, simulator
from librabft_simulator_tpu.sim.simulator import dedupe_buffers

engine_name, structural, b, num_steps, batched, digest = json.loads(sys.argv[1])
engine = parallel_sim if engine_name == "lane" else simulator
# The ledger row's `structural` field IS a SimParams repr (the compile
# ledger records it per entry) — rebuild the exact params the suite
# compiled.  max_clock is normalized to 0 there (runtime data, outside
# the jit key), so one immediately-halting chunk call is enough to
# build-or-load the executable.  Parsed with ast, NOT eval: the ledger
# file lives at a predictable /tmp path, and a dataclass repr that stops
# being literal kwargs should fail loudly here, not execute.
call = ast.parse(structural, mode="eval").body
if not (isinstance(call, ast.Call) and isinstance(call.func, ast.Name)
        and call.func.id == "SimParams" and not call.args):
    raise ValueError("structural field is not a SimParams(...) repr: "
                     + structural[:120])
p = SimParams(**{k.arg: ast.literal_eval(k.value) for k in call.keywords})
if batched:
    st = dedupe_buffers(engine.init_batch(p, np.arange(b, dtype=np.uint32)))
else:
    st = dedupe_buffers(engine.init_state(p, 0))
run = engine.make_run_fn(p, num_steps, batched=batched, digest=digest)
out = run(st)
jax.block_until_ready(jax.tree_util.tree_leaves(out)[0])
for e in tledger.get().compiles:
    print("  compile", e["key"], e["shapes"], e["cache"],
          "compile_s=%%.1f" %% e["compile_s"])
"""


def warm_from_ledger(root: str, path: str) -> None:
    """Warm/export EXACTLY the chunk executables a previous run compiled,
    read from its streamed runtime ledger (``LIBRABFT_LEDGER_OUT`` NDJSON
    — e.g. /tmp/_t1_ledger.ndjson from the last tier-1 run).

    This makes the warm matrix DATA-DRIVEN: the static SHAPES above cover
    the known referee contracts, but the suite compiles many more
    (engine, structural, num_steps, batch) combinations than anyone
    should hand-maintain — the ledger already records every one of them,
    with the full structural-params repr.  One child per distinct key
    (the fresh-process export protocol); entries already in the AOT store
    just load and exit, so repeat runs are cheap.  Sharded rows are
    skipped (their mesh/wrap context lives in SHARDED_SHAPES)."""
    import re

    from librabft_simulator_tpu.telemetry.ledger import read_ndjson

    try:
        rows = read_ndjson(path)
    except (OSError, ValueError) as e:
        print(f"[warm_cache] --from-ledger: cannot read {path}: {e}",
              file=sys.stderr)
        return
    seen = {}
    for r in rows:
        if r.get("kind") != "compile":
            continue
        if r.get("engine") not in ("serial", "lane"):
            continue  # sharded/sanitize flavors ride their static lists
        if not r.get("structural") or r.get("num_steps") is None:
            continue
        b = None
        if r.get("batched"):
            m = re.match(r"\((\d+)", str(r.get("shapes", "")))
            if not m:
                continue
            b = int(m.group(1))
        key = (r["engine"], r["structural"], b, int(r["num_steps"]),
               bool(r.get("batched")), bool(r.get("digest")))
        seen.setdefault(key, r)
    print(f"[warm_cache] --from-ledger {path}: {len(seen)} distinct "
          f"chunk executables", flush=True)
    import json

    env = _build_env()
    for key in seen:
        engine_name, structural, b, num_steps, batched, digest = key
        r = subprocess.run(
            [sys.executable, "-c", LEDGER_CHILD % {"root": root},
             json.dumps(list(key))],
            cwd=root, env=env)
        print(f"[warm_cache] ledger shape {engine_name} b={b} "
              f"steps={num_steps} digest={digest}: rc={r.returncode}",
              flush=True)
    _print_store_summary()


def _build_env(**extra) -> dict:
    """Child environment: the AOT build knob rides along — children
    export their chunk executables into the store unless the caller
    disabled the store (``LIBRABFT_AOT=0``) or pinned the write knob
    themselves."""
    from librabft_simulator_tpu.utils import aot

    env = dict(os.environ, **extra)
    if aot.enabled():
        env.setdefault("LIBRABFT_AOT_WRITE", "1")
    return env


def _print_store_summary() -> None:
    """One line on what the build produced (jax-free manifest read)."""
    from librabft_simulator_tpu.utils import aot

    man = aot.read_manifest()
    if man is None:
        print("[warm_cache] aot store: none (exports disabled or failed)",
              flush=True)
        return
    entries = man.get("entries", [])
    total = sum(e.get("size_bytes", 0) for e in entries)
    print(f"[warm_cache] aot store {aot.store_dir()}: {len(entries)} "
          f"executables, {total / 1e6:.1f} MB "
          f"(python -m librabft_simulator_tpu.utils.aot --list)", flush=True)


def warm_fleet(root: str) -> None:
    """Compile every BENCH_FLEET ladder rung into the AOT store +
    bench.py's persistent cache (one subprocess per rung is the ladder's
    own protocol, so shapes — dp, per-shard batch, chunk — match the real
    run exactly and ``BENCH_FLEET=1 python bench.py`` afterwards pays
    deserialize seconds, not compile)."""
    # BENCH_FLEET_AOT_AB=0: warming wants the production-path executables
    # only — the A/B's LIBRABFT_AOT=0 leg deliberately re-measures the
    # compile this build exists to pre-pay.
    env = _build_env(BENCH_FLEET="1", BENCH_FLEET_REPS="1",
                     BENCH_FLEET_AOT_AB="0",
                     BENCH_FLEET_OUT="/tmp/warm_fleet.json")
    r = subprocess.run([sys.executable, "bench.py"], cwd=root, env=env,
                       stdout=subprocess.DEVNULL)
    print(f"[warm_cache] fleet ladder: rc={r.returncode}", flush=True)
    _print_store_summary()


def warm_macro(root: str) -> None:
    """Compile every BENCH_MACRO K-ladder rung into the AOT store +
    bench.py's persistent cache (one subprocess per rung is the ladder's
    own protocol; the census compile is skipped — only the timed chunk
    executables warm, which is what a real BENCH_MACRO=1 run re-censuses
    anyway)."""
    env = _build_env(BENCH_MACRO="1", BENCH_REPS="1",
                     BENCH_MACRO_CENSUS="0",
                     BENCH_MACRO_OUT="/tmp/warm_macro.json")
    r = subprocess.run([sys.executable, "bench.py"], cwd=root, env=env,
                       stdout=subprocess.DEVNULL)
    print(f"[warm_cache] macro ladder: rc={r.returncode}", flush=True)
    _print_store_summary()


def warm_bench(root: str) -> None:
    """Compile every bench/sweep shape into the AOT store + bench.py's
    persistent cache.

    One child per config (a single long-lived process accumulating many big
    compiles risks the serialize-segfault the module docstring describes).
    """
    env = _build_env(BENCH_PLATFORM="cpu", BENCH_REPS="1")
    # The headline bench shape (both engines), then every sweep config.
    # Count derived from bench.sweep_configs in a CHILD (importing bench
    # here would run its module-level backend attach in this process).
    n_cfg = int(subprocess.run(
        [sys.executable, "-c",
         "import bench; print(len(bench.sweep_configs(1.0)))"],
        cwd=root, env=env, capture_output=True, text=True,
        check=True).stdout.strip())
    r = subprocess.run([sys.executable, "bench.py"], cwd=root, env=env,
                       stdout=subprocess.DEVNULL)
    print(f"[warm_cache] bench headline: rc={r.returncode}", flush=True)
    for i in range(1, n_cfg + 1):
        env_i = dict(env, BENCH_SWEEP="1", BENCH_SWEEP_ONLY=str(i),
                     BENCH_SWEEP_OUT="/tmp/warm_sweep.json")
        r = subprocess.run([sys.executable, "bench.py"], cwd=root, env=env_i,
                           stdout=subprocess.DEVNULL)
        print(f"[warm_cache] sweep config {i}: rc={r.returncode}", flush=True)
    _print_store_summary()


def main():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if "--list" in sys.argv:
        for e, kw, b, c in SHAPES:
            print(e, kw, b, c)
        for e, kw, b, c, dp in SHARDED_SHAPES:
            print(e, kw, b, c, f"dp={dp}")
        for e, kw, b, c in SANITIZE_SHAPES:
            print(e, kw, b, c, "sanitize")
        return
    if "--bench" in sys.argv:
        warm_bench(root)
        return
    if "--fleet" in sys.argv:
        warm_fleet(root)
        return
    if "--macro" in sys.argv:
        warm_macro(root)
        return
    if "--from-ledger" in sys.argv:
        warm_from_ledger(
            root, sys.argv[sys.argv.index("--from-ledger") + 1])
        return
    import json

    env = _build_env()
    for e, kw, b, c in SHAPES:
        r = subprocess.run(
            [sys.executable, "-c", CHILD % {"root": root},
             json.dumps([e, kw, b, c])],
            cwd=root, env=env)
        print(f"[warm_cache] {e} {kw} b={b} chunk={c}: rc={r.returncode}",
              flush=True)
    for e, kw, b, c, dp in SHARDED_SHAPES:
        r = subprocess.run(
            [sys.executable, "-c", SHARDED_CHILD % {"root": root},
             json.dumps([e, kw, b, c, dp])],
            cwd=root, env=env)
        print(f"[warm_cache] sharded {e} {kw} b={b} chunk={c} dp={dp}: "
              f"rc={r.returncode}", flush=True)
    for e, kw, b, c in SANITIZE_SHAPES:
        r = subprocess.run(
            [sys.executable, "-c", SANITIZE_CHILD % {"root": root},
             json.dumps([e, kw, b, c])],
            cwd=root, env=env)
        print(f"[warm_cache] sanitize {e} {kw} b={b} chunk={c}: "
              f"rc={r.returncode}", flush=True)
    _print_store_summary()


if __name__ == "__main__":
    main()
