"""Pre-warm the XLA persistent compile cache for the test suite.

The suite (tests/conftest.py) runs the cache READ-ONLY: cache writes
(``executable.serialize()``) segfault jaxlib in long-running processes that
have accumulated many large compiles.  In a fresh process per shape the
writes are reliable — so this script compiles each heavy (engine, shape)
pair in its own subprocess, after which the suite runs from cache.

Usage:  python scripts/warm_cache.py            # all shapes
        python scripts/warm_cache.py --list     # show shapes
"""
import os
import subprocess
import sys

SHAPES = [
    # (engine, SimParams kwargs, batch) — representative heavy shapes from
    # the suite.  Batch size is part of the compiled shape: batch=None means
    # an UNBATCHED single-instance run (how the parity tests drive the
    # serial engine); the parallel entries mirror tests/test_parallel_sim.py
    # small_params batches and tests/test_epoch_handoff.py boundary_params.
    ("serial", {}, None),                                 # parity default
    ("serial", {"n_nodes": 4}, None),
    ("serial", {"n_nodes": 3, "commands_per_epoch": 6}, None),  # handoff
    ("parallel",
     {"n_nodes": 4, "delay_kind": "uniform", "window": 8, "chain_k": 2,
      "commit_log": 16}, 6),
    ("parallel",
     {"n_nodes": 4, "delay_kind": "uniform", "window": 8, "chain_k": 2,
      "commit_log": 16}, 8),
    ("parallel",
     {"n_nodes": 3, "commands_per_epoch": 6, "delay_kind": "uniform",
      "drop_prob": 0.1, "window": 16, "chain_k": 4}, 8),
]

CHILD = r"""
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import sys, json
import numpy as np
sys.path.insert(0, %(root)r)
from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.sim import parallel_sim, simulator
from librabft_simulator_tpu.sim.simulator import dedupe_buffers

engine_name, kw, batch = json.loads(sys.argv[1])
engine = parallel_sim if engine_name == "parallel" else simulator
p = SimParams(max_clock=500, **kw)
if batch is None:
    st = dedupe_buffers(engine.init_state(p, 0))
    run = engine.make_run_fn(p, 256, batched=False)
else:
    st = dedupe_buffers(engine.init_batch(p, np.arange(batch, dtype=np.uint32)))
    run = engine.make_run_fn(p, 256)
jax.block_until_ready(run(st))
print("warmed", engine_name, kw, batch)
"""


def main():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if "--list" in sys.argv:
        for e, kw, b in SHAPES:
            print(e, kw, b)
        return
    import json

    for e, kw, b in SHAPES:
        r = subprocess.run(
            [sys.executable, "-c", CHILD % {"root": root},
             json.dumps([e, kw, b])],
            cwd=root)
        print(f"[warm_cache] {e} {kw} b={b}: rc={r.returncode}", flush=True)


if __name__ == "__main__":
    main()
