"""The perf-regression sentinel: a canonical micro-bench matrix with a
noise-aware gate over a committed rolling history.

The repo has rich throughput benches (bench.py, scripts/tpu_ladder.py)
but nothing in CI noticed a *regression*: a host-loop change that halved
dispatch throughput would sail through tier-1 green because correctness
referees don't time anything.  This sentinel closes that hole:

* **Canonical rungs** — six micro measurements at the warmed
  ``tests/fleet_shapes.py`` contracts (so the AOT prebuild pays the
  compiles, and the timed windows measure dispatch, not tracing):

  - ``serial_step``  — serial engine events/s (FLEET_SER_KW, B=FLEET_B,
    chunk=FLEET_CHUNK; higher is better)
  - ``lane_step``    — lane engine events/s (FLEET_LANE_KW; higher)
  - ``fleet_chunk``  — 2-shard ``run_sharded`` steady-state seconds per
    dispatched chunk, from the runtime ledger's dispatch+poll spans
    (lower is better)
  - ``macro_k16``    — serial events/s at macro_k=16 (the K-amortization
    headline; higher)
  - ``aot_ttfc``     — ``pipeline_stats`` time_to_first_chunk_s of the
    first sharded run in this process, cold compile / AOT load included
    (lower; measured once — later reps are warm by construction)
  - ``serve_admit``  — resident-fleet submitted->admitted request
    latency (median over SERVE_SLOTS requests; lower)
  - ``ring_dispatch`` — 2-shard ``run_sharded`` seconds per RETIRED
    chunk under the device dispatch wrap (FLEET_RING_SER_KW: the
    in-graph ring loop at ring_k=FLEET_RING_K; lower) — the
    fleet_chunk twin whose outer program retires up to K chunks per
    host round-trip

* **History** — every run appends ONE NDJSON row (schema
  ``bench_history`` v1, telemetry/schema.py) to the committed
  ``BENCH_HISTORY.ndjson``; each rung's value is the median of
  ``$BENCH_SENTINEL_REPS`` repeats, so one scheduler hiccup cannot
  poison a row.

* **Gate** — a rung regresses only when it is worse than the median of
  its last <= 5 prior history values by more than the tolerance from
  scripts/budgets.py (``bench_sentinel_tol_pct``; override
  ``$BENCH_SENTINEL_TOL_PCT``).  Fewer than 3 prior rows -> verdict
  ``baseline`` and rc 0 (the gate arms itself; the first CI runs seed
  history instead of failing).  Any regression -> loud ``perf-regress``
  ledger spans + rc 2.

* **Self-test hook** — ``$BENCH_SENTINEL_SLOWDOWN=3`` scales every
  recorded value 3x worse *after* measurement (rates divided, times
  multiplied), so tests/test_observatory.py can prove the gate fires on
  a seeded slowdown and stays green on an honest re-run, without
  actually burning 3x the CPU.

Usage:
    python scripts/perf_sentinel.py                 # measure+append+judge
    python scripts/perf_sentinel.py --no-append     # measure+judge only
    BENCH_SENTINEL_RUNGS=serial_step,lane_step ...  # subset of rungs
"""

import argparse
import json
import os
import platform
import statistics
import sys
import time

# CPU by default; the rungs are host-dispatch micro shapes.  Must happen
# before the jax import.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))  # budgets
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir, "tests"))  # fleet_shapes

from budgets import BUDGETS  # noqa: E402

#: Env knobs (registered in audit/knobs.py; S3 lint contract).
REPS_ENV = "BENCH_SENTINEL_REPS"
OUT_ENV = "BENCH_SENTINEL_OUT"
RUNGS_ENV = "BENCH_SENTINEL_RUNGS"
TOL_ENV = "BENCH_SENTINEL_TOL_PCT"
SLOWDOWN_ENV = "BENCH_SENTINEL_SLOWDOWN"

DEFAULT_REPS = 3
#: Baseline window: median of the last <= 5 prior rows per rung.
BASELINE_WINDOW = 5
#: The gate stays advisory until this many prior rows exist per rung.
MIN_HISTORY = 3
#: Chunks per fleet_chunk/aot_ttfc measurement run (chunk 0 is the cold
#: one; the remaining ones are the steady-state sample).
FLEET_CHUNKS = 4

#: rung name -> (direction, unit).  "higher" = bigger is better.
RUNG_META = {
    "serial_step": ("higher", "events/s"),
    "lane_step": ("higher", "events/s"),
    "fleet_chunk": ("lower", "s/chunk"),
    "macro_k16": ("higher", "events/s"),
    "aot_ttfc": ("lower", "s"),
    "serve_admit": ("lower", "s"),
    "ring_dispatch": ("lower", "s/chunk"),
}

PERF_REGRESS = "perf-regress"


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def default_history_path() -> str:
    return os.path.join(repo_root(), "BENCH_HISTORY.ndjson")


def _median(vals):
    return float(statistics.median(vals))


# ---------------------------------------------------------------------------
# Measurement — jax imports stay inside so --help / judging history stays
# cheap and importable from jax-free contexts.
# ---------------------------------------------------------------------------


def _collect_samples(rungs, reps: int) -> dict:
    """The heavy half of :func:`measure`: run each requested rung
    ``reps`` times and return the raw ``{name: [float, ...]}`` samples.
    Split out so the gate self-test (tests/test_observatory.py) can
    monkeypatch the measurement while exercising the REAL median /
    slowdown / history / verdict plumbing."""
    import jax

    from fleet_shapes import (FLEET_B, FLEET_CHUNK, FLEET_LANE_KW,
                              FLEET_RING_SER_KW, FLEET_SER_KW,
                              SERVE_CHUNK, SERVE_DP, SERVE_SLOTS)
    from librabft_simulator_tpu.core.types import SimParams
    from librabft_simulator_tpu.parallel import mesh as mesh_ops
    from librabft_simulator_tpu.parallel import sharded
    from librabft_simulator_tpu.sim import parallel_sim as PE
    from librabft_simulator_tpu.sim import simulator as S
    from librabft_simulator_tpu.telemetry import ledger as tledger
    from librabft_simulator_tpu.telemetry import report as treport

    lg = tledger.get()
    samples: dict = {name: [] for name in rungs}
    ttfc_first = None

    def probe_rate(engine, p):
        out = treport.probe_occupancy(engine, p, B=FLEET_B,
                                      chunk=FLEET_CHUNK, reps=3)
        return float(out["events_per_sec"])

    p_ser = SimParams(max_clock=120, **FLEET_SER_KW)
    p_lane = SimParams(max_clock=150, **FLEET_LANE_KW)
    # max_clock is runtime data (outside the jit key) — the K rung keeps
    # the warmed micro capacities and just raises the horizon so the
    # 16-events-per-step window doesn't halt the fleet mid-measurement.
    p_k16 = SimParams(max_clock=100_000,
                      **dict(FLEET_SER_KW, macro_k=16))

    mesh2 = None
    if {"fleet_chunk", "aot_ttfc", "ring_dispatch"} & set(rungs):
        if len(jax.devices()) < 2:
            raise SystemExit("perf_sentinel: fleet_chunk/aot_ttfc need 2 "
                             "devices (XLA_FLAGS host device count)")
        mesh2 = mesh_ops.make_mesh(n_dp=2, n_mp=1,
                                   devices=jax.devices()[:2])

    def fleet_chunk_once():
        """One sharded run; returns (steady s/chunk, ttfc_s)."""
        st = S.init_batch(p_ser, sharded.fleet_seeds(0, FLEET_B))
        sharded.run_sharded(p_ser, mesh2, st,
                            num_steps=FLEET_CHUNK * FLEET_CHUNKS,
                            chunk=FLEET_CHUNK)
        pipe = lg.pipeline_stats()
        steady = max(int(pipe.get("chunks", 0)) - 1, 1)
        per_chunk = (float(pipe.get("dispatch_s", 0.0))
                     + float(pipe.get("poll_s", 0.0))) / steady
        return per_chunk, float(pipe.get("time_to_first_chunk_s", 0.0))

    # Same horizon as fleet_chunk — max_clock is runtime data, so the
    # warmed ring executable (warm_cache SHARDED_SHAPES) is reused.
    p_ring = SimParams(max_clock=120, **FLEET_RING_SER_KW)

    def ring_dispatch_once():
        """One device-wrap sharded run; returns seconds per RETIRED
        chunk (host wall over the in-graph ring loop's chunk count)."""
        st = S.init_batch(p_ring, sharded.fleet_seeds(0, FLEET_B))
        sharded.run_sharded(p_ring, mesh2, st,
                            num_steps=FLEET_CHUNK * FLEET_CHUNKS,
                            chunk=FLEET_CHUNK)
        pipe = lg.pipeline_stats()
        ring = lg.ring_stats()
        if not ring:
            raise SystemExit("perf_sentinel: ring_dispatch run recorded "
                             "no ring polls (wrap='device' not armed?)")
        return ((float(pipe.get("dispatch_s", 0.0))
                 + float(pipe.get("poll_s", 0.0)))
                / max(int(ring["retired_chunks"]), 1))

    svc = None
    if "serve_admit" in rungs:
        from librabft_simulator_tpu.serve import scenario as sc
        from librabft_simulator_tpu.serve.service import ResidentFleet
        import tempfile
        mesh_s = mesh_ops.make_mesh(n_dp=SERVE_DP, n_mp=1,
                                    devices=jax.devices()[:SERVE_DP])
        serve_dir = tempfile.mkdtemp(prefix="perf_sentinel_serve_")
        serve_out = os.path.join(serve_dir, "serve.ndjson")
        svc = ResidentFleet(SimParams(max_clock=300, **FLEET_SER_KW),
                            slots=SERVE_SLOTS, mesh=mesh_s,
                            chunk=SERVE_CHUNK, out=serve_out)

        def serve_admit_once(rep):
            for i in range(SERVE_SLOTS):
                svc.submit(sc.ScenarioSpec(max_clock=60,
                                           seed=100 * rep + i))
            svc.drain()
            rows = tledger.read_ndjson(serve_out)
            subm, lat = {}, []
            for r in rows:
                if r.get("kind") != "request":
                    continue
                if r.get("event") == "submitted":
                    subm[r["id"]] = float(r["t_s"])
                elif r.get("event") == "admitted" and r["id"] in subm:
                    lat.append(float(r["t_s"]) - subm.pop(r["id"]))
            if not lat:
                raise SystemExit("perf_sentinel: serve stream recorded no "
                                 "submitted->admitted pairs")
            return _median(lat)

    try:
        for rep in range(reps):
            if "serial_step" in rungs:
                samples["serial_step"].append(probe_rate(S, p_ser))
            if "lane_step" in rungs:
                samples["lane_step"].append(probe_rate(PE, p_lane))
            if "macro_k16" in rungs:
                samples["macro_k16"].append(probe_rate(S, p_k16))
            if "fleet_chunk" in rungs or "aot_ttfc" in rungs:
                per_chunk, ttfc = fleet_chunk_once()
                if "fleet_chunk" in rungs:
                    samples["fleet_chunk"].append(per_chunk)
                if ttfc_first is None:
                    ttfc_first = ttfc
            if "ring_dispatch" in rungs:
                samples["ring_dispatch"].append(ring_dispatch_once())
            if "serve_admit" in rungs:
                samples["serve_admit"].append(serve_admit_once(rep))
    finally:
        if svc is not None:
            import shutil
            svc.close()
            shutil.rmtree(os.path.dirname(serve_out), ignore_errors=True)

    if "aot_ttfc" in rungs:
        # Only the first run pays the compile/AOT load — later reps in
        # this process are warm and would measure something else.
        samples["aot_ttfc"] = [ttfc_first]
    return samples


def measure(rungs, reps: int) -> dict:
    """Median-of-reps per rung, slowdown hook applied; returns
    ``{name: {"value", "unit", "direction", "reps"}}``."""
    samples = _collect_samples(rungs, reps)
    slowdown = float(os.environ.get(SLOWDOWN_ENV, "") or 1.0)
    out = {}
    for name in rungs:
        direction, unit = RUNG_META[name]
        value = _median(samples[name])
        if slowdown != 1.0:
            value = value / slowdown if direction == "higher" \
                else value * slowdown
        out[name] = {"value": round(value, 6), "unit": unit,
                     "direction": direction, "reps": len(samples[name])}
    return out


# ---------------------------------------------------------------------------
# History + gate — jax-free.
# ---------------------------------------------------------------------------


def load_history(path: str) -> list:
    """Prior bench rows, oldest first (tolerant of a torn final line)."""
    from librabft_simulator_tpu.telemetry import ledger as tledger
    if not os.path.exists(path):
        return []
    return [r for r in tledger.read_ndjson(path, tolerant=True)
            if r.get("kind") == "bench"]


def judge(rungs_out: dict, history: list, tol_pct: float) -> dict:
    """Per-rung verdicts vs the rolling baseline.

    Returns ``{name: {"verdict": ok|baseline|regress, "baseline": float|None,
    "n_history": int}}``.
    """
    verdicts = {}
    tol = tol_pct / 100.0
    for name, row in rungs_out.items():
        prior = [float(h["rungs"][name]["value"]) for h in history
                 if name in h.get("rungs", {})]
        n = len(prior)
        if n < MIN_HISTORY:
            verdicts[name] = {"verdict": "baseline", "baseline": None,
                              "n_history": n}
            continue
        base = _median(prior[-BASELINE_WINDOW:])
        value = float(row["value"])
        if row["direction"] == "higher":
            regress = value < base / (1.0 + tol)
        else:
            regress = value > base * (1.0 + tol)
        verdicts[name] = {"verdict": "regress" if regress else "ok",
                          "baseline": round(base, 6), "n_history": n}
    return verdicts


def append_row(path: str, row: dict) -> None:
    with open(path, "a") as f:
        f.write(json.dumps(row) + "\n")
        f.flush()


def main(argv=None) -> int:
    from librabft_simulator_tpu.telemetry import schema as tschema

    ap = argparse.ArgumentParser(
        description="canonical micro-bench matrix + perf-regression gate")
    ap.add_argument("--reps", type=int,
                    default=int(os.environ.get(REPS_ENV, "")
                                or DEFAULT_REPS),
                    help="measurements per rung; the row records the "
                         "median (env BENCH_SENTINEL_REPS)")
    ap.add_argument("--out", default=os.environ.get(OUT_ENV, "")
                    or default_history_path(),
                    help="history NDJSON path (env BENCH_SENTINEL_OUT; "
                         "default BENCH_HISTORY.ndjson at the repo root)")
    ap.add_argument("--no-append", action="store_true",
                    help="measure + judge but leave history untouched")
    args = ap.parse_args(argv)

    names = [s for s in (os.environ.get(RUNGS_ENV, "") or
                         ",".join(RUNG_META)).split(",") if s]
    unknown = [s for s in names if s not in RUNG_META]
    if unknown:
        raise SystemExit(f"perf_sentinel: unknown rung(s) {unknown}; "
                         f"known: {sorted(RUNG_META)}")

    tol_pct = float(os.environ.get(TOL_ENV, "")
                    or BUDGETS["bench_sentinel_tol_pct"])

    history = load_history(args.out)
    rungs_out = measure(names, max(args.reps, 1))
    verdicts = judge(rungs_out, history, tol_pct)

    import jax

    from librabft_simulator_tpu.telemetry import ledger as tledger
    row = {
        "kind": "bench",
        "schema": "bench_history",
        "bench_history_version": tschema.BENCH_HISTORY_VERSION,
        "t_unix": round(time.time(), 3),
        "platform": jax.devices()[0].platform,
        "host": platform.machine(),
        "jax": jax.__version__,
        "reps": max(args.reps, 1),
        "tol_pct": tol_pct,
        "rungs": rungs_out,
        "verdicts": {k: v["verdict"] for k, v in verdicts.items()},
    }
    if not args.no_append:
        append_row(args.out, row)

    lg = tledger.get()
    regressed = []
    for name in names:
        r, v = rungs_out[name], verdicts[name]
        base = v["baseline"]
        base_s = f"{base:g}" if base is not None else "-"
        print(f"{name:12s} {r['value']:>12g} {r['unit']:9s} "
              f"baseline={base_s:>10s} n={v['n_history']} "
              f"-> {v['verdict']}")
        if v["verdict"] == "regress":
            regressed.append(name)
            with lg.span(PERF_REGRESS, rung=name, value=r["value"],
                         baseline=base, unit=r["unit"],
                         direction=r["direction"], tol_pct=tol_pct):
                pass
    if regressed:
        print(f"perf_sentinel: REGRESSION in {regressed} "
              f"(tolerance {tol_pct:g}% over median of last "
              f"{BASELINE_WINDOW} rows; see {args.out})")
        return 2
    armed = all(v["n_history"] >= MIN_HISTORY for v in verdicts.values())
    print(f"perf_sentinel: ok ({'gate armed' if armed else 'seeding baseline'}"
          f", {len(history)} prior rows, history -> {args.out})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
