"""Compile-time kernel/fusion census for the jitted serial step.

Round-5 on-chip profiling showed the step is kernel-count-bound on TPU
(~330 tiny fusions per step; per-kernel dispatch, not FLOPs, sets the
ceiling).  This script makes that number a compile-time regression metric
that does NOT need the TPU tunnel: it lowers the jitted serial step via
``jax.jit(...).lower(...).compile()`` and counts instructions by opcode in
the optimized HLO — fusions being the headline (each fusion is one kernel
launch; unfused whiles/scatters/sorts add their own dispatches).

Three graphs are censused:

* ``baseline_pre_pr`` — the exact pre-PR lowering (unpacked leaves,
  scatter queue writes, ungated handlers), reproducible forever from the
  current tree, so the "before" number never goes stale;
* ``cpu_default``      — what CPU lowering runs after this PR (proven
  scatter forms kept; only handler gating differs from baseline);
* ``tpu_shape``        — what TPU lowering runs after this PR (packed
  state planes + dense one-hot queue writes + handler gating).

On a CPU-only host the counts are a *proxy* for the TPU lowering (XLA's
fusion decisions differ per backend, but the op-count structure the
backends fuse from is the same graph); rerun on chip when the tunnel is
up.

Usage:
    JAX_PLATFORMS=cpu python scripts/kernel_census.py
    python scripts/kernel_census.py --assert-max 250   # CI regression gate
    python scripts/kernel_census.py --n 4 --batch 2048 --out CENSUS.json
"""

from __future__ import annotations

import argparse
import collections
import dataclasses
import functools
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))  # budgets

os.environ.setdefault("JAX_PLATFORMS", "cpu")

if "--sharded" in sys.argv or "--assert-budgets" in sys.argv or any(
        a.startswith("--assert-sharded-max") or a.startswith("--assert-ring")
        for a in sys.argv):
    # The sharded census needs virtual devices BEFORE backend init (and
    # --assert-sharded-max implies --sharded, so it must trigger the shim
    # too — argparse runs far too late to force the device count).
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from librabft_simulator_tpu.core import packing  # noqa: E402
from librabft_simulator_tpu.core.types import SimParams  # noqa: E402
from librabft_simulator_tpu.sim import simulator as S  # noqa: E402

# Computation header: "%name (params) -> type {" (optionally "ENTRY ...").
# Params may carry TUPLE-typed entries (nested parens) — e.g. a while
# body's "(param.1: (s32[], s32[2048,9]))" — so the param group is a
# greedy any-match up to the "->", not a paren-free "\([^)]*\)" (round
# 11: the old form silently skipped those headers and misattributed
# their instructions to the previous computation).
_COMP_RE = re.compile(r"^(ENTRY\s+)?%?([\w.-]+)\s*(\(.*\))?\s*->.*{")
# Opcode(s) on an instruction line: "%name = type opcode(...)".  Long
# tuple types embed "/*index=N*/" markers whose '=' broke the lazy
# "[^=]*?" bridge (round 11: while instructions went uncounted);
# hlo_counts strips comments per line before matching.
_OP_RE = re.compile(r"=\s[^=]*?\s([\w-]+)\(")
_COMMENT_RE = re.compile(r"/\*.*?\*/")

# Ops that launch (or serialize into) their own kernel(s) when not fused.
_DISPATCH_OPS = ("fusion", "scatter", "sort", "dot", "custom-call", "rng",
                 "while", "conditional", "all-reduce", "all-gather")


def hlo_counts(txt: str) -> dict:
    """Count ops per computation in optimized HLO text.

    The headline metric is ``top_fusions``: fusion calls in the entry
    computation plus while-loop bodies — i.e. fusion sites in the
    dispatched program (XLA CPU also *nests* fusions inside fusion bodies;
    those are inlined by the emitter, not separate launches, so raw
    fusion-instruction totals overcount).  While bodies are counted ONCE
    (static dispatch sites), the same convention the pre-existing protocol
    whiles always had.  On the round-5 toolchain the pre-PR
    ``top_dispatch`` count (334) matched the ~330 per-step kernels the
    on-chip profiler saw, which is what qualifies this as the kernel-count
    proxy; the round-11 container's jaxlib/XLA update changed both the
    optimizer's fusion decisions and the HLO text format (tuple-typed
    header params, ``/*index=N*/`` type comments), so the parser was
    repaired and every budget re-baselined — see scripts/budgets.py
    provenance and PERF_NOTES round 11."""
    comp = None
    per = collections.Counter()
    while_bodies = set()
    for line in txt.splitlines():
        line = _COMMENT_RE.sub("", line)
        m = _COMP_RE.match(line)
        if m:
            comp = ("ENTRY:" if m.group(1) else "") + m.group(2)
            continue
        for op in _OP_RE.findall(line):
            per[(comp or "?", op)] += 1
        for b in re.findall(r"while\(.*?\).*?body=%?([\w.-]+)", line):
            while_bodies.add(b)
    entry = next((c for c, _ in per if c.startswith("ENTRY:")), None)

    def top(pred):
        return sum(v for (c, op), v in per.items()
                   if (c == entry or c.split(":")[-1] in while_bodies)
                   and pred(op))

    ops = collections.Counter()
    for (_, op), v in per.items():
        ops[op] += v
    return {
        "top_fusions": top(lambda op: op == "fusion"),
        "top_dispatch": top(lambda op: op in _DISPATCH_OPS),
        "total_fusions": ops.get("fusion", 0),
        "instructions": sum(ops.values()),
        "whiles": ops.get("while", 0),
        "scatters": ops.get("scatter", 0),
        "conditionals": ops.get("conditional", 0),
    }


def census_step(p: SimParams, batch: int) -> dict:
    """Lower + compile the jitted vmapped serial step; count HLO ops.

    For packed params the step is lowered on the packed plane state (the
    steady-state scan body), not the pack/unpack boundary.  With
    ``p.macro_k > 1`` the censused unit is the engine's own
    ``macro_step`` (the K-event rolled inner scan — the dispatched unit
    of work), and ``events_per_dispatch``/``fusions_per_event`` record
    the amortization: K events retire against one program's fusion
    sites, so fusions per event drops ~K-fold while a K=1 macro census
    is the bare step graph exactly (macro_step returns it unwrapped)."""
    st = S.init_batch(p, np.arange(batch, dtype=np.uint32))
    if p.packed:
        st = packing.pack_state(p, st)
    dt = jnp.asarray(p.delay_table())
    du = jnp.asarray(p.duration_table())
    k = S.macro_k_of(p)
    fn = S.macro_step if k > 1 else S.step
    f = jax.jit(jax.vmap(functools.partial(fn, p),
                         in_axes=(None, None, 0)))
    compiled = f.lower(dt, du, st).compile()
    out = hlo_counts(compiled.as_text())
    out["events_per_dispatch"] = k
    out["fusions_per_event"] = round(out["top_fusions"] / k, 1)
    return out


def census_lane(p: SimParams, batch: int) -> dict:
    """Lower + compile the jitted vmapped LANE-engine window step; count
    HLO ops — the parallel engine's flavor of :func:`census_step`
    (introduced for the adversary plane, whose per-link horizon
    derivation lives in this engine's graph).  The tables and the
    conservative lookahead are bound exactly as the engine's own
    ``make_run_fn`` binds them."""
    from librabft_simulator_tpu.sim import parallel_sim as PS

    st = PS.init_batch(p, np.arange(batch, dtype=np.uint32))
    if p.packed:
        st = PS.pack_pstate(p, st)
    dt = jnp.asarray(p.delay_table())
    du = jnp.asarray(p.duration_table())
    f = jax.jit(jax.vmap(
        functools.partial(PS.step, p, dt, du, PS.d_min_of(p))))
    compiled = f.lower(st).compile()
    return hlo_counts(compiled.as_text())


def census_sharded(p: SimParams, batch: int, dp: int) -> dict:
    """Per-shard census of the dp-fleet runtime (parallel/sharded.py).

    Lowers + compiles the shard_map-wrapped one-chunk runner (scan length 1
    == one step per instance, plus the in-graph [D] fleet-health digest —
    telemetry/stream.py — that replaced the bare halted_count reduction) on
    a dp-shard CPU mesh and counts HLO ops.  Under shard_map the optimized
    module IS the per-shard program, so ``top_fusions`` here is the kernel
    count each dispatch engine pays per step — the dp scaling premise
    (collective-free shards) holds exactly when this stays at the
    single-chip census plus the O(1) halt-reduction overhead."""
    from librabft_simulator_tpu.parallel import mesh as mesh_ops
    from librabft_simulator_tpu.parallel import sharded

    mesh = mesh_ops.make_mesh(n_dp=dp, n_mp=1, devices=jax.devices()[:dp])
    st = S.init_batch(p, np.arange(batch, dtype=np.uint32))
    st, _ = sharded.pad_to_multiple(p, st, mesh.size)
    st = mesh_ops.shard_batch(mesh, st)
    run = sharded.make_sharded_run_fn(p, mesh, 1)
    compiled = run.lower(st).compile()
    return hlo_counts(compiled.as_text())


def census_ring(p: SimParams, batch: int, dp: int, ring_k: int) -> dict:
    """Per-shard census of the DEVICE dispatch wrap (SimParams.wrap=
    "device"; parallel/sharded.py): the ring runner whose outer program
    retires up to ``ring_k`` chunks in an in-graph while loop, streaming
    each retired chunk's digest into the on-device ``[ring_k, 13]`` ring.
    The chunk body is the identical graph to :func:`census_sharded`'s, so
    the fusion count should be that census plus O(1) while/ring-update
    overhead — FLAT in ring_k (the ring loop is rolled; a budget climbing
    with K means the loop body got duplicated).  ``cap`` is lowered as a
    traced scalar, exactly as the host passes it."""
    from librabft_simulator_tpu.parallel import mesh as mesh_ops
    from librabft_simulator_tpu.parallel import sharded

    p = dataclasses.replace(p, wrap="device", ring_k=ring_k)
    mesh = mesh_ops.make_mesh(n_dp=dp, n_mp=1, devices=jax.devices()[:dp])
    st = S.init_batch(p, np.arange(batch, dtype=np.uint32))
    st, _ = sharded.pad_to_multiple(p, st, mesh.size)
    st = mesh_ops.shard_batch(mesh, st)
    run = sharded.make_sharded_run_fn(p, mesh, 1)
    compiled = run.lower(st, np.int32(ring_k)).compile()
    return hlo_counts(compiled.as_text())


MODES = {
    # The pre-PR serial-step graph, exactly: per-leaf node state,
    # .at[] queue scatters, handlers computed unconditionally.
    "baseline_pre_pr": dict(packed=False, dense_writes="scatter",
                            gate_handlers=False),
    # Post-PR CPU default (xops.resolve_params on a CPU backend) — by
    # design the exact pre-PR graph: every TPU form is gated off on CPU.
    "cpu_default": dict(packed=False, dense_writes="scatter",
                        gate_handlers=False),
    # Post-PR TPU lowering shape (xops.resolve_params on a TPU backend).
    "tpu_shape": dict(packed=True, dense_writes="dense",
                      gate_handlers=True),
    # TPU shape + the telemetry plane/flight recorder (telemetry/plane.py).
    # Telemetry OFF must leave tpu_shape untouched (the --assert-max gate);
    # telemetry ON pays its own recorded budget (--assert-telemetry-max,
    # KERNEL_CENSUS_r07.json) — the cost of observing must be bounded too.
    "tpu_shape_telemetry": dict(packed=True, dense_writes="dense",
                                gate_handlers=True, telemetry=True),
    # TPU shape + the consensus watchdog (telemetry/stream.py).  Watchdog
    # OFF must leave tpu_shape untouched (same zero-cost-when-disabled
    # contract as telemetry); ON pays its own budget
    # (--assert-watchdog-max) — the per-step detectors are elementwise
    # forms over the tiny [WD] plane, so the increment should stay small.
    "tpu_shape_watchdog": dict(packed=True, dense_writes="dense",
                               gate_handlers=True, watchdog=True),
    # The full streaming configuration (telemetry + watchdog): what a
    # production fleet runs when it both records planes and streams live
    # digests; recorded for the artifact, gated transitively by the two
    # budgets above.
    "tpu_shape_telemetry_watchdog": dict(packed=True, dense_writes="dense",
                                         gate_handlers=True, telemetry=True,
                                         watchdog=True),
    # K-event macro-steps (SimParams.macro_k; sim/simulator.py
    # macro_step): the dispatched unit retires K events via a rolled
    # fixed-K inner scan, so the program's fusion count stays ~flat
    # while fusions PER EVENT drops ~K-fold — the events/kernel
    # multiplier on top of PR 1's kernels/step cut.  macro_k=1 is the
    # bare tpu_shape graph exactly (no wrapper; the --assert-max gate
    # covers it); the K rungs carry their own budgets
    # (--assert-k4-max / --assert-k16-max, scripts/budgets.py).
    "tpu_shape_k4": dict(packed=True, dense_writes="dense",
                         gate_handlers=True, macro_k=4),
    "tpu_shape_k16": dict(packed=True, dense_writes="dense",
                          gate_handlers=True, macro_k=16),
    # Per-slot scenario plane (SimParams.scenario; serve/scenario.py):
    # the delay table becomes a traced per-slot [T] row and the commit
    # rule a traced 2-vs-3-chain select, so ONE executable serves a
    # heterogeneous scenario fleet.  Scenario OFF must leave tpu_shape
    # untouched (the --assert-max gate — zero-width leaves compile out);
    # ON pays its own budget (--assert-scenario-max) — the per-slot
    # selects' fusion cost is gated here, not guessed.
    "tpu_shape_scenario": dict(packed=True, dense_writes="dense",
                               gate_handlers=True, scenario=True),
    # Adversary plane (SimParams.adversary; adversary/): the windowed
    # attack-schedule decode, per-link delay adds, and partition cuts.
    # Adversary OFF must leave tpu_shape untouched (the --assert-max
    # gate — zero-width leaves compile out; the graph audit's R6
    # adversary arm is the static twin); ON pays its own budget
    # (--assert-adversary-max).  The lane flavor is censused separately
    # below (census_lane) under --assert-adversary-lane-max.
    "tpu_shape_adversary": dict(packed=True, dense_writes="dense",
                                gate_handlers=True, adversary=True),
}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--n", type=int, default=4)
    ap.add_argument("--batch", type=int, default=2048)
    ap.add_argument("--unroll", action="store_true",
                    help="census the unrolled-scan variants too")
    ap.add_argument("--assert-max", type=int, default=None,
                    help="exit nonzero if the tpu_shape fusion count "
                         "exceeds this budget (CI regression gate)")
    ap.add_argument("--assert-telemetry-max", type=int, default=None,
                    help="exit nonzero if the tpu_shape_telemetry fusion "
                         "count exceeds this budget (CI regression gate; "
                         "recorded in KERNEL_CENSUS_r07.json)")
    ap.add_argument("--assert-watchdog-max", type=int, default=None,
                    help="exit nonzero if the tpu_shape_watchdog fusion "
                         "count exceeds this budget (CI regression gate; "
                         "the watchdog-OFF graph is covered by --assert-max "
                         "— disabled detectors must cost zero kernels)")
    ap.add_argument("--assert-k4-max", type=int, default=None,
                    help="exit nonzero if the tpu_shape_k4 macro-step "
                         "fusion count exceeds this budget (CI gate; "
                         "the K=4 dispatched program — 4 events/launch)")
    ap.add_argument("--assert-k16-max", type=int, default=None,
                    help="exit nonzero if the tpu_shape_k16 macro-step "
                         "fusion count exceeds this budget (CI gate)")
    ap.add_argument("--assert-scenario-max", type=int, default=None,
                    help="exit nonzero if the tpu_shape_scenario fusion "
                         "count exceeds this budget (CI gate; the "
                         "scenario-plane per-slot select graph — "
                         "scenario OFF is covered by --assert-max)")
    ap.add_argument("--assert-adversary-max", type=int, default=None,
                    help="exit nonzero if the tpu_shape_adversary fusion "
                         "count exceeds this budget (CI gate; the "
                         "attack-schedule/link/partition decode graph — "
                         "adversary OFF is covered by --assert-max)")
    ap.add_argument("--assert-adversary-lane-max", type=int, default=None,
                    help="exit nonzero if the LANE engine's adversary "
                         "window-step fusion count exceeds this budget "
                         "(CI gate; includes the per-link horizon "
                         "derivation)")
    ap.add_argument("--sharded", action="store_true",
                    help="also census the per-shard dp-fleet program "
                         "(shard_map runner on a 2-shard virtual CPU mesh)")
    ap.add_argument("--sharded-dp", type=int, default=2,
                    help="dp shard count for --sharded (default 2)")
    ap.add_argument("--assert-sharded-max", type=int, default=None,
                    help="exit nonzero if the per-shard tpu_shape fusion "
                         "count exceeds this budget (CI gate; implies "
                         "--sharded)")
    ap.add_argument("--assert-ring-k4-max", type=int, default=None,
                    help="exit nonzero if the per-shard DEVICE-wrap ring "
                         "runner's fusion count at ring_k=4 exceeds this "
                         "budget (CI gate; implies --sharded)")
    ap.add_argument("--assert-ring-k16-max", type=int, default=None,
                    help="exit nonzero if the ring_k=16 ring runner's "
                         "fusion count exceeds this budget (CI gate; the "
                         "k4/k16 pair pins the count FLAT in ring_k — the "
                         "ring loop is rolled; implies --sharded)")
    ap.add_argument("--assert-budgets", action="store_true",
                    help="apply all four census budgets from "
                         "scripts/budgets.py (the CI single source) — "
                         "equivalent to passing each --assert-* flag "
                         "with its recorded budget")
    ap.add_argument("--out", default=None,
                    help="write the full census JSON here")
    args = ap.parse_args()
    if args.assert_budgets:
        # Budgets live in ONE place (scripts/budgets.py); the source lint
        # flags any literal restated here.
        import budgets as _budgets
        b = _budgets.BUDGETS
        if args.assert_max is None:
            args.assert_max = b["census_off"]
        if args.assert_telemetry_max is None:
            args.assert_telemetry_max = b["census_telemetry"]
        if args.assert_watchdog_max is None:
            args.assert_watchdog_max = b["census_watchdog"]
        if args.assert_sharded_max is None:
            args.assert_sharded_max = b["census_sharded"]
        if args.assert_k4_max is None:
            args.assert_k4_max = b["census_k4"]
        if args.assert_k16_max is None:
            args.assert_k16_max = b["census_k16"]
        if args.assert_scenario_max is None:
            args.assert_scenario_max = b["census_scenario"]
        if args.assert_adversary_max is None:
            args.assert_adversary_max = b["census_adversary"]
        if args.assert_adversary_lane_max is None:
            args.assert_adversary_lane_max = b["census_adversary_lane"]
        if args.assert_ring_k4_max is None:
            args.assert_ring_k4_max = b["census_ring_k4"]
        if args.assert_ring_k16_max is None:
            args.assert_ring_k16_max = b["census_ring_k16"]
    if (args.assert_sharded_max is not None
            or args.assert_ring_k4_max is not None
            or args.assert_ring_k16_max is not None):
        args.sharded = True

    from librabft_simulator_tpu.telemetry import plane as tplane
    from librabft_simulator_tpu.telemetry import stream as tstream

    base = SimParams(n_nodes=args.n, delay_kind="uniform", max_clock=2**30,
                     queue_cap=max(32, 4 * args.n), unroll=args.unroll)
    out = {
        "platform": jax.default_backend(),
        "config": {"n_nodes": args.n, "batch": args.batch,
                   "queue_cap": base.queue_cap, "unroll": args.unroll},
        # The exact observability configuration these counts were taken
        # under: the frozen slot-map version, plane/digest/watchdog widths,
        # and the stall threshold (a compile key — the census is invalid
        # for a build whose registry differs).
        "stream": {
            "registry_version": tstream.REGISTRY_VERSION,
            "plane_width": tplane.width(dataclasses.replace(
                base, telemetry=True)),
            "digest_width": tstream.DIGEST_WIDTH,
            "wd_width": tstream.WD_WIDTH,
            "watchdog_stall_events": base.watchdog_stall_events,
        },
        "modes": {},
    }
    seen = {}  # identical mode dicts share one compile (cpu_default is
    # baseline_pre_pr by construction; compiling it twice buys nothing)
    for name, kw in MODES.items():
        key = tuple(sorted(kw.items()))
        if key not in seen:
            p = dataclasses.replace(base, **kw)
            seen[key] = census_step(p, args.batch)
        out["modes"][name] = c = seen[key]
        per_ev = (f" ev/dispatch={c['events_per_dispatch']:2d} "
                  f"fusions/ev={c['fusions_per_event']:6.1f}"
                  if c.get("events_per_dispatch", 1) > 1 else "")
        print(f"{name:18s} top_fusions={c['top_fusions']:4d} "
              f"top_dispatch={c['top_dispatch']:4d} "
              f"total_fusions={c['total_fusions']:5d} "
              f"whiles={c['whiles']} scatters={c['scatters']}{per_ev}",
              flush=True)

    # Lane-engine adversary flavor: the per-link-horizon graph lives in
    # the parallel engine, so it gets its own compile + budget.
    p_lane = dataclasses.replace(base, **MODES["tpu_shape_adversary"])
    c = census_lane(p_lane, args.batch)
    out["modes"]["tpu_shape_adversary_lane"] = c
    print(f"{'tpu_shape_adversary_lane':18s} top_fusions={c['top_fusions']:4d} "
          f"top_dispatch={c['top_dispatch']:4d} "
          f"total_fusions={c['total_fusions']:5d} "
          f"whiles={c['whiles']} scatters={c['scatters']} (lane engine)",
          flush=True)

    if args.sharded:
        p_sh = dataclasses.replace(base, **MODES["tpu_shape"])
        c = census_sharded(p_sh, args.batch, args.sharded_dp)
        out["modes"]["sharded_tpu_shape"] = c
        out["sharded_dp"] = args.sharded_dp
        print(f"{'sharded_tpu_shape':18s} top_fusions={c['top_fusions']:4d} "
              f"top_dispatch={c['top_dispatch']:4d} "
              f"total_fusions={c['total_fusions']:5d} "
              f"whiles={c['whiles']} scatters={c['scatters']} "
              f"(per shard, dp={args.sharded_dp})", flush=True)
        for rk in (4, 16):
            c = census_ring(p_sh, args.batch, args.sharded_dp, rk)
            out["modes"][f"sharded_ring_k{rk}"] = c
            print(f"{f'sharded_ring_k{rk}':18s} "
                  f"top_fusions={c['top_fusions']:4d} "
                  f"top_dispatch={c['top_dispatch']:4d} "
                  f"total_fusions={c['total_fusions']:5d} "
                  f"whiles={c['whiles']} scatters={c['scatters']} "
                  f"(device wrap, per shard, dp={args.sharded_dp})",
                  flush=True)

    before = out["modes"]["baseline_pre_pr"]["top_fusions"]
    after = out["modes"]["tpu_shape"]["top_fusions"]
    pct = 100.0 * (before - after) / max(before, 1)
    out["fusion_reduction_pct_tpu_shape_vs_baseline"] = round(pct, 1)
    print(f"tpu_shape vs baseline_pre_pr: {before} -> {after} top-level "
          f"fusions ({pct:+.1f}% reduction)")

    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
        print(f"wrote {args.out}")
    if args.assert_max is not None and after > args.assert_max:
        print(f"FAIL: tpu_shape top-level fusion count {after} exceeds "
              f"budget {args.assert_max}", file=sys.stderr)
        return 1
    tel = out["modes"]["tpu_shape_telemetry"]["top_fusions"]
    if args.assert_telemetry_max is not None and tel > args.assert_telemetry_max:
        print(f"FAIL: tpu_shape_telemetry top-level fusion count {tel} "
              f"exceeds budget {args.assert_telemetry_max}", file=sys.stderr)
        return 1
    wdc = out["modes"]["tpu_shape_watchdog"]["top_fusions"]
    if args.assert_watchdog_max is not None and wdc > args.assert_watchdog_max:
        print(f"FAIL: tpu_shape_watchdog top-level fusion count {wdc} "
              f"exceeds budget {args.assert_watchdog_max}", file=sys.stderr)
        return 1
    for kname, budget in (("tpu_shape_k4", args.assert_k4_max),
                          ("tpu_shape_k16", args.assert_k16_max),
                          ("tpu_shape_scenario", args.assert_scenario_max),
                          ("tpu_shape_adversary", args.assert_adversary_max),
                          ("tpu_shape_adversary_lane",
                           args.assert_adversary_lane_max)):
        kc = out["modes"][kname]["top_fusions"]
        if budget is not None and kc > budget:
            print(f"FAIL: {kname} fusion count {kc} exceeds "
                  f"budget {budget}", file=sys.stderr)
            return 1
    if args.assert_sharded_max is not None:
        sh = out["modes"]["sharded_tpu_shape"]["top_fusions"]
        if sh > args.assert_sharded_max:
            print(f"FAIL: sharded_tpu_shape per-shard fusion count {sh} "
                  f"exceeds budget {args.assert_sharded_max}",
                  file=sys.stderr)
            return 1
    for rk, budget in ((4, args.assert_ring_k4_max),
                       (16, args.assert_ring_k16_max)):
        if budget is None:
            continue
        rc = out["modes"][f"sharded_ring_k{rk}"]["top_fusions"]
        if rc > budget:
            print(f"FAIL: sharded_ring_k{rk} per-shard fusion count {rc} "
                  f"exceeds budget {budget}", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
