"""Run every static-analysis pass and gate CI on a clean result.

Passes (librabft_simulator_tpu/audit/):

1. **Graph lint** — traces both engines' step functions in every lowering
   flavor (cpu_default, tpu_shape, telemetry/watchdog twins, the
   scenario-plane flavor tpu_shape_scenario plus its off-inert /
   read-only-pass-through R6 arm, the serial engine's K-macro rungs
   tpu_shape_k{4,16} plus the macro_k=1-identity pin, the dp-sharded
   runner) and enforces jaxpr rules R1-R6 (graph_lint.py).
   Tracing never compiles, so the whole matrix costs ~2 minutes, vs the
   census's XLA compiles — which is why CI runs this FIRST.
2. **Source lint** — AST rules S1-S4 (host libs in traced code,
   unsanctioned host syncs, unregistered env knobs, duplicated budget
   literals) + the README knob-table sync check (source_lint.py).
3. **Donation & aliasing verifier** — D-rules (donation_lint.py): the
   per-flavor donation map read from each runner's STAGED lowering
   (``.lower()`` only — no XLA compile) and pinned against
   scripts/budgets.py DONATION, plus the AST rules D2
   (dedupe-before-placement: the PR-9 bare-device_put-into-donating-
   runner segfault class) and D3 (host use-after-donate).
4. **Host-concurrency lint** — C-rules (concurrency_lint.py, pure AST):
   C1 every cross-process wait bounded (the wedged-gloo-collective hang
   class), C2 lock discipline over registered shared state, C3 NDJSON
   rows flushed per write.
5. **Compiled-HLO audit** (``hlo_lint.py``; skip with ``--no-hlo``) —
   compiles the warmed micro-fleet chunk runners on the visible backend
   and audits the OPTIMIZED module: scatter instruction class + site
   provenance (the R1-waived sites must be the only scatter sources),
   the digest-only small root at the executable level, and donation
   alias survival.  The only pass that invokes XLA; on a warm
   persistent cache it costs seconds (tunnel item 8: on-chip = flag
   flip).
6. **Sanitizer smoke** (``--sanitize``) — compiles and runs the
   checkify-instrumented chunk of both engines at the warmed micro fleet
   shapes (plus the scenario-plane flavor); any tripped state invariant
   fails.  Off by default (it compiles); scripts/warm_cache.py runs it
   to pre-warm the debug executables, and tests/test_audit.py smokes it
   in tier-1.

Output: a GRAPH_AUDIT artifact (rule -> status -> offending eqn/source
site) via ``--out``; ``--assert-clean`` exits nonzero on any error-grade
finding (waived findings are recorded but pass).

Usage:
    JAX_PLATFORMS=cpu python scripts/graph_audit.py --assert-clean
    python scripts/graph_audit.py --shape micro --sanitize
    python scripts/graph_audit.py --no-hlo --no-donation   # jaxpr+AST only
    python scripts/graph_audit.py --out GRAPH_AUDIT_r19.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# The sharded-runner rules (R5, R6/mp) trace a 2-shard mesh: force virtual
# devices BEFORE backend init (same shim as kernel_census --sharded).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()


def run_sanitize_smoke() -> list:
    """Compile + run the checked chunk of both engines at the warmed micro
    fleet shapes; returns error findings (graph_lint.Finding-shaped)."""
    import numpy as np

    from librabft_simulator_tpu.audit import sanitize
    from librabft_simulator_tpu.audit.graph_lint import Finding
    from librabft_simulator_tpu.core.types import SimParams
    from librabft_simulator_tpu.sim import parallel_sim, simulator

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tests"))
    from fleet_shapes import FLEET_B, FLEET_CHUNK, FLEET_LANE_KW, \
        FLEET_SCENARIO_SER_KW, FLEET_SER_KW

    findings = []
    for name, eng, kw in (("serial", simulator, FLEET_SER_KW),
                          ("parallel", parallel_sim, FLEET_LANE_KW),
                          ("serial-scenario", simulator,
                           FLEET_SCENARIO_SER_KW)):
        p = SimParams(max_clock=500, **kw)
        st = eng.init_batch(p, np.arange(FLEET_B, dtype=np.uint32))
        try:
            sanitize.run_checked(p, st, FLEET_CHUNK, batched=True,
                                 engine=eng)
        except Exception as e:  # noqa: BLE001 — any trip/compile failure
            findings.append(Finding(
                "SAN", f"sanitize/{name}", "error",
                f"checkify sanitizer tripped or failed on the {name} "
                f"engine micro chunk: {type(e).__name__}: "
                f"{str(e)[:200]}", ""))
    return findings


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--shape", choices=("census", "micro"),
                    default="census",
                    help="audit shape: the kernel-census shape (CI "
                         "default) or the micro fleet shape (fast)")
    ap.add_argument("--engines", default="serial,lane",
                    help="comma list of engines to graph-audit")
    ap.add_argument("--no-sharded", action="store_true",
                    help="skip the sharded-runner rules (R5, R6/mp)")
    ap.add_argument("--no-source", action="store_true",
                    help="skip the AST source lint")
    ap.add_argument("--no-donation", action="store_true",
                    help="skip the donation/aliasing verifier (D-rules: "
                         "staged lowerings + the dedupe/use-after-donate "
                         "AST rules)")
    ap.add_argument("--no-concurrency", action="store_true",
                    help="skip the host-concurrency lint (C-rules)")
    ap.add_argument("--no-hlo", action="store_true",
                    help="skip the compiled-HLO audit (the one pass that "
                         "invokes XLA; seconds on a warm persistent "
                         "cache, minutes cold)")
    ap.add_argument("--sanitize", action="store_true",
                    help="also compile+run the checkify sanitizer smoke "
                         "at the micro fleet shapes")
    ap.add_argument("--out", default=None,
                    help="write the GRAPH_AUDIT JSON artifact here")
    ap.add_argument("--assert-clean", action="store_true",
                    help="exit nonzero on any error-grade finding")
    args = ap.parse_args()

    t0 = time.time()
    from librabft_simulator_tpu.audit import graph_lint, source_lint

    out = graph_lint.audit_all(
        shape=args.shape,
        engines=tuple(e for e in args.engines.split(",") if e),
        sharded=not args.no_sharded)
    out["graph_seconds"] = round(time.time() - t0, 1)

    if not args.no_source:
        src = source_lint.run()
        out["findings"] += [f.to_json() for f in src]
        out["source_findings"] = len(src)
    if not args.no_donation:
        from librabft_simulator_tpu.audit import donation_lint

        t1 = time.time()
        # Always the micro shapes: a donation map is a LEAF-COUNT
        # property (donate_argnums x pytree structure), independent of
        # n_nodes/capacities — micro keeps the staging matrix cheap and
        # the budgets.py DONATION pins shape-free.
        df, dstats = donation_lint.audit_donation(shape="micro")
        df += donation_lint.run_source()
        out["findings"] += [f.to_json() for f in df]
        out["donation"] = {"flavors": dstats,
                           "seconds": round(time.time() - t1, 1)}
    if not args.no_concurrency:
        from librabft_simulator_tpu.audit import concurrency_lint

        cf = concurrency_lint.run()
        out["findings"] += [f.to_json() for f in cf]
        out["concurrency_findings"] = len(cf)
    if not args.no_hlo:
        from librabft_simulator_tpu.audit import hlo_lint

        t1 = time.time()
        hf, hstats = hlo_lint.audit_hlo()
        out["findings"] += [f.to_json() for f in hf]
        out["hlo"] = {"flavors": hstats,
                      "seconds": round(time.time() - t1, 1)}
    if args.sanitize:
        san = run_sanitize_smoke()
        out["findings"] += [f.to_json() for f in san]
        out["sanitize"] = "fail" if san else "ok"

    errors = [f for f in out["findings"] if f["severity"] == "error"]
    waived = [f for f in out["findings"] if f["severity"] == "waived"]
    out["n_errors"], out["clean"] = len(errors), not errors
    out["elapsed_seconds"] = round(time.time() - t0, 1)

    for f in out["findings"]:
        tag = "WAIVED" if f["severity"] == "waived" else "ERROR "
        site = f" [{f['site']}]" if f["site"] else ""
        print(f"{tag} {f['rule']:3s} {f['flavor']:24s}"
              f" {f['summary'][:110]}{site}")
    print(f"graph audit: {len(errors)} error(s), {len(waived)} waived, "
          f"{len(out['flavors'])} flavors, {out['elapsed_seconds']}s",
          flush=True)

    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
        print(f"wrote {args.out}")
    if args.assert_clean and errors:
        print("FAIL: graph audit not clean (--assert-clean)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
