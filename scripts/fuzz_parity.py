"""Differential fuzz: jitted serial engine vs the pure-Python oracle.

A failing trial no longer just prints and vanishes: the failure path writes
a first-divergence MINIDUMP artifact (FUZZ_MINIDUMP_<n>.json) with the
seed, full SimParams, the differing observable-state leaves at the first
diverging event (scripts/debug_parity.py's lockstep leaf-diff), and the
telemetry flight-recorder tail of the failing run — a replayable record
instead of a bisection session.

The framework's core claim is bit-determinism across implementations; the
test suite pins ~15 hand-picked configs.  This fuzzer covers the runtime-
parameter space cheaply by exploiting ``SimParams.structural()``
memoization: delay kind/params, drop_prob, and max_clock are runtime data,
so HUNDREDS of (delay, drop, horizon, seed) combinations run on a handful
of XLA compiles.  Structural shapes (n_nodes, window, chain_k,
commit_chain, handoff) rotate slowly since each costs a fresh compile.

Every trial asserts the full test_parity invariant set: event/clock/stamp/
message counters, per-node committed chains, store heads, and lock rounds.

Usage: python scripts/fuzz_parity.py [minutes]   # default 30
    FUZZ_PACKED=1 python scripts/fuzz_parity.py 10   # packed-plane engine
    FUZZ_MACRO_K=1 python scripts/fuzz_parity.py 10  # randomize macro_k
    FUZZ_SCENARIO=1 python scripts/fuzz_parity.py 10 # heterogeneous fleets
    FUZZ_ADVERSARY=1 python scripts/fuzz_parity.py 10 # attack programs
Writes FUZZ_PARITY_r05.json (FUZZ_PARITY_r06_packed.json under
FUZZ_PACKED=1; FUZZ_PARITY_r11_macro.json under FUZZ_MACRO_K=1;
FUZZ_PARITY_r14_scenario.json under FUZZ_SCENARIO=1;
FUZZ_PARITY_r17_adversary.json under FUZZ_ADVERSARY=1)
{trials, structural_shapes, macro_trials, failures[]}.

FUZZ_ADVERSARY=1 is the adversary-engine campaign (adversary/): per-trial
randomized attack programs — windowed equivocation/silence/forged QCs,
targeted + leader-targeted delay, per-link extra-delay matrices,
partitions-with-heal — installed as plane data on the adversary-armed
serial engine and pinned per event against OracleSim(attack=...); the
minidump records the DECODED program (the counterexample reporter).

FUZZ_SCENARIO=1 is the serving-regime campaign: every trial builds a
small fleet whose slots each draw an INDEPENDENT random scenario row
(delay distribution, drop rate, horizon, 2-vs-3 commit chain, Byzantine
schedule, rng seed — serve/scenario.py), runs the whole batch on ONE
scenario-armed executable, and pins every slot against its own dedicated
oracle — the heterogeneous-fleet parity claim of the resident fleet
service, fuzzed.  Minidumps record the full plane (per-slot spec dicts),
which replays the trial exactly.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))  # debug_parity

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from librabft_simulator_tpu.utils.cache import setup_compile_cache  # noqa: E402

setup_compile_cache()

import numpy as np  # noqa: E402

from librabft_simulator_tpu.core.types import SimParams  # noqa: E402
from librabft_simulator_tpu.oracle.sim import OracleSim  # noqa: E402
from librabft_simulator_tpu.sim import byzantine  # noqa: E402
from librabft_simulator_tpu.sim import simulator as S  # noqa: E402

# Slow axis: each entry is one XLA compile.  Mix of protocol variants.
STRUCTURAL = [
    dict(n_nodes=3),
    dict(n_nodes=4),
    dict(n_nodes=4, commit_chain=2),
    dict(n_nodes=5, window=8, chain_k=2, commit_log=16),
    dict(n_nodes=4, shuffle_receivers=True),
    dict(n_nodes=3, commands_per_epoch=60, handoff_epochs=2),
    dict(n_nodes=6, queue_cap=48),
]

# FUZZ_PACKED=1 runs every trial on the packed-plane engine
# (core/packing.py) — the jitted side packs state into [N, S] planes while
# the oracle stays leaf-based, so any packing defect shows as a parity
# divergence.  Strict parse (xops._bool_env): "off" must not mean on.
from librabft_simulator_tpu.utils import xops  # noqa: E402

PACKED = xops._bool_env("FUZZ_PACKED") or False

# FUZZ_MACRO_K=1 randomizes the serial engine's K-event macro-step width
# per trial (sim/simulator.py macro_step): the jitted side retires K
# events per dispatched step while the oracle stays strictly per-event,
# so any macro defect — a dropped halt gate, a carry mixup in the inner
# scan, an off-by-one in the chunk budget — shows as a parity divergence.
# K is a compile key, so the K axis multiplies structural compiles; the
# set stays small and the runtime axes keep riding structural()
# memoization.  Minidumps record macro_k via the full params dict and the
# failure row.
MACRO = xops._bool_env("FUZZ_MACRO_K") or False
MACRO_KS = (1, 2, 4, 8)

# FUZZ_SCENARIO=1: heterogeneous-fleet trials on the per-slot scenario
# plane (see module docstring).  The structural axis shrinks to SHAPES
# only — commit_chain and the whole delay family are per-slot data now,
# which is exactly the executable-count collapse being fuzzed.
SCENARIO = xops._bool_env("FUZZ_SCENARIO") or False
SCENARIO_SLOTS = 4
SCENARIO_STRUCTURAL = [
    dict(n_nodes=3),
    dict(n_nodes=4),
    dict(n_nodes=5, window=8, chain_k=2, commit_log=16),
]

# FUZZ_ADVERSARY=1: adversary-engine campaign (adversary/) — every trial
# draws a RANDOM attack program (windowed equivocation / targeted silence
# / forged QCs / targeted + leader-targeted delay, an optional per-link
# extra-delay matrix, an optional partition-with-heal), installs it on
# the adversary-armed serial engine, and checks the FULL oracle-parity
# invariant set against OracleSim(attack=...) — the per-event mirror of
# the window decode, link delays, and partition cuts.  The plane is
# per-slot DATA, so the whole campaign rides a couple of structural
# compiles.  Minidumps record the DECODED program (HostPlane.describe),
# the counterexample-reporting contract.  LIBRABFT_ADV_WINDOWS sets the
# plane's window capacity W (a compile key; default 4).
ADVERSARY = xops._bool_env("FUZZ_ADVERSARY") or False
ADV_WINDOWS = int(os.environ.get("LIBRABFT_ADV_WINDOWS", "") or 4)
ADV_STRUCTURAL = [
    dict(n_nodes=4),
    dict(n_nodes=5, window=8, chain_k=2, commit_log=16),
    dict(n_nodes=7, queue_cap=48),
]

DELAYS = [
    dict(delay_kind="lognormal", delay_mean=10.0, delay_variance=4.0),
    dict(delay_kind="lognormal", delay_mean=25.0, delay_variance=16.0),
    dict(delay_kind="uniform"),
    dict(delay_kind="pareto", delay_pareto_scale=5.0, delay_pareto_alpha=1.5),
    dict(delay_kind="pareto", delay_pareto_scale=2.0, delay_pareto_alpha=2.5),
    dict(delay_kind="constant"),
]


def committed_chain(st, node, H):
    cc = int(st.ctx.commit_count[node])
    return [(int(st.ctx.log_depth[node, i % H]), int(st.ctx.log_tag[node, i % H]))
            for i in range(max(cc - H, 0), cc)]


def compare_oracle(p: SimParams, st, orc, byz_any) -> list[str]:
    """The full test_parity invariant set between an (unbatched, host)
    engine state and a finished oracle — shared by the static trials and
    the per-slot checks of the FUZZ_SCENARIO heterogeneous-fleet mode."""
    errs = []
    for name, a, b in [
        ("n_events", int(st.n_events), orc.n_events),
        ("clock", int(st.clock), orc.clock),
        ("stamp_ctr", int(st.stamp_ctr), orc.stamp_ctr),
        ("msgs_sent", int(st.n_msgs_sent), orc.n_msgs_sent),
        ("msgs_dropped", int(st.n_msgs_dropped), orc.n_msgs_dropped),
        ("queue_full", int(st.n_queue_full), orc.n_queue_full),
    ]:
        if a != b:
            errs.append(f"{name}: jax={a} oracle={b}")
    H = st.ctx.log_depth.shape[-1]
    for a in range(p.n_nodes):
        if committed_chain(st, a, H) != orc.committed_chain(a):
            errs.append(f"node {a} committed chain differs")
        if int(st.store.current_round[a]) != orc.stores[a].current_round:
            errs.append(f"node {a} current_round differs")
        if int(st.node.locked_round[a]) != orc.nxs[a].locked_round:
            errs.append(f"node {a} locked_round differs")
    # Safety invariant: across honest nodes, one tag per committed depth
    # (holds for any f <= floor((n-1)/3) attacker mix the sampler draws).
    # Reuses the suite's reference checker on a batch-of-1 view.
    st1 = jax.tree.map(lambda x: np.asarray(x)[None], st)
    if not byzantine.check_safety_reference(st1, honest_mask=~byz_any)[0]:
        errs.append("SAFETY: honest nodes committed conflicting tags")
    return errs


def one_trial(p: SimParams, seed: int, byz=None) -> list[str]:
    kw = dict(byz or {})
    st = S.init_state(p, seed, **{k: np.asarray(v) for k, v in kw.items()})
    st = S.run_to_completion(p, st)
    orc = OracleSim(p, seed, **{k: list(v) for k, v in kw.items()}).run()
    byz_any = np.zeros(p.n_nodes, bool)
    for v in (byz or {}).values():
        byz_any |= np.asarray(v, bool)
    return compare_oracle(p, st, orc, byz_any)


def scenario_trial(base_kw: dict, rng) -> tuple[list, dict]:
    """One heterogeneous-fleet trial: SCENARIO_SLOTS independent random
    scenario rows on ONE scenario-armed executable, each slot pinned
    against its own dedicated oracle.  Returns (specs, {slot: errors})."""
    from librabft_simulator_tpu.serve import scenario as sc

    base = SimParams(**base_kw, packed=PACKED)
    p_sc = dataclasses.replace(base, scenario=True)
    specs = []
    for _ in range(SCENARIO_SLOTS):
        runtime = dict(rng.choice(DELAYS))
        n = base.n_nodes
        f_max = (n - 1) // 3
        kind, f = "honest", 0
        if f_max and rng.random() < 0.4:
            kind = rng.choice(["equivocate", "silent", "forge_qc"])
            f = rng.randrange(1, f_max + 1)
        specs.append(sc.ScenarioSpec(
            **runtime,
            drop_prob=rng.choice([0.0, 0.0, 0.02, 0.05, 0.15]),
            max_clock=rng.choice([400, 800, 1500]),
            commit_chain=rng.choice([2, 3]),
            byz_kind=kind, byz_f=f,
            seed=rng.randrange(2**31)))
    st = sc.init_specs(p_sc, specs)
    st = S.run_to_completion(p_sc, st, batched=True)
    host = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), st)
    slot_errs = {}
    for i, spec in enumerate(specs):
        p_i = spec.to_params(base)
        eq, silent, forge = (np.asarray(m) for m in spec.byz_masks(base))
        orc = OracleSim(p_i, spec.seed,
                        byz_equivocate=list(eq), byz_silent=list(silent),
                        byz_forge_qc=list(forge)).run()
        st_i = jax.tree.map(lambda x, ii=i: x[ii], host)
        errs = compare_oracle(p_i, st_i, orc, eq | silent | forge)
        if errs:
            slot_errs[i] = errs
    return specs, slot_errs


def adversary_trial(base_kw: dict, rng) -> tuple[dict, dict, list[str]]:
    """One randomized attack-program trial: engine (plane installed) vs
    oracle (HostPlane mirror).  Returns (runtime axes, program dict with
    its decoded form, errors)."""
    from librabft_simulator_tpu.adversary import dsl as adsl

    runtime = dict(rng.choice(DELAYS))
    runtime["drop_prob"] = rng.choice([0.0, 0.0, 0.02, 0.05])
    runtime["max_clock"] = rng.choice([400, 800, 1500])
    p = SimParams(**base_kw, **runtime, packed=PACKED, adversary=True,
                  adv_windows=ADV_WINDOWS)
    seed = rng.randrange(2**31)
    prog = adsl.sample_program(p, rng, horizon=runtime["max_clock"])
    st = S.init_state(p, seed)
    st = prog.install(p, st)
    st = S.run_to_completion(p, st)
    orc = OracleSim(p, seed, attack=prog).run()
    byz_any = np.isin(np.arange(p.n_nodes),
                      sorted(adsl.byz_targets(prog)))
    errs = compare_oracle(p, st, orc, byz_any)
    info = dict(seed=seed, attack=prog.to_dict(),
                decoded=prog.host_plane(p).describe())
    return runtime, info, errs


def write_minidump(p: SimParams, seed: int, structural: dict, runtime: dict,
                   byz, errs: list[str], index: int) -> str:
    """First-divergence minidump for a failing trial.

    Reuses scripts/debug_parity.py's lockstep leaf-diff to locate the first
    diverging event, then reruns the trial with telemetry on to capture the
    flight-recorder tail and metrics plane of the failing trajectory.  Each
    piece is best-effort: a crash while diagnosing must not lose the parts
    already gathered (or the original failure record)."""
    import debug_parity

    dump = dict(seed=seed, structural=structural, runtime=runtime, byz=byz,
                errors=errs, params=dataclasses.asdict(p))
    try:
        # Event budget matches one_trial's run_to_completion ceiling
        # (400 chunks x 256 steps), so a replay can never give up before
        # the trial's own horizon; first_divergence marks exhaustion
        # explicitly if it somehow does.
        dump["first_divergence"] = debug_parity.first_divergence(
            p, seed, byz=byz, max_ev=400 * 256)
    except Exception as e:  # noqa: BLE001 - diagnostics must not mask the failure
        dump["first_divergence_error"] = f"{type(e).__name__}: {e}"[:300]
    try:
        from librabft_simulator_tpu.telemetry import report as tel_report

        p_tel = dataclasses.replace(p, telemetry=True, flight_cap=64)
        kw = {k: np.asarray(v) for k, v in (byz or {}).items()}
        st = S.run_to_completion(p_tel, S.init_state(p_tel, seed, **kw))
        dump["flight_tail"] = tel_report.decode_flight(p_tel, st)
        dump["telemetry"] = tel_report.metrics_dict(p_tel, st)
    except Exception as e:  # noqa: BLE001
        dump["flight_tail_error"] = f"{type(e).__name__}: {e}"[:300]
    # Seed-keyed name: campaigns restart `index` at 0, and a later campaign
    # must not overwrite an earlier one's forensic artifact (same seed =>
    # same deterministic trial => identical dump, so that collision is
    # harmless by construction).
    path = f"FUZZ_MINIDUMP_{index:04d}_seed{seed}.json"
    with open(path, "w") as f:
        json.dump(dump, f, indent=1, default=str)
    return path


def main() -> int:
    minutes = float(sys.argv[1]) if len(sys.argv) > 1 else 30.0
    deadline = time.time() + minutes * 60
    rng = random.Random(0xF12A)
    trials = 0
    byz_trials = {"byz_equivocate": 0, "byz_silent": 0, "byz_forge_qc": 0}
    macro_trials: dict = {}
    shapes_used = set()
    failures = []
    adv_stats = {"partition": 0, "link": 0, "windows": 0}
    while time.time() < deadline:
        if ADVERSARY:
            sk = rng.randrange(len(ADV_STRUCTURAL))
            structural = ADV_STRUCTURAL[sk]
            runtime, info, errs = adversary_trial(structural, rng)
            trials += 1
            shapes_used.add((sk, 1))
            dec = info["decoded"]
            adv_stats["windows"] += len(dec["windows"])
            adv_stats["partition"] += int(dec["heal"] != 0
                                          and len(set(dec["groups"])) > 1)
            adv_stats["link"] += int(bool(dec["link"])
                                     and any(any(r) for r in dec["link"]))
            for w in dec["windows"]:
                key = "byz_" + w["behavior"]
                if key in byz_trials:
                    byz_trials[key] += 1
            if errs:
                dump = dict(structural=structural, runtime=runtime,
                            errors=errs, **info)
                path = (f"FUZZ_MINIDUMP_ADV_{len(failures):04d}_"
                        f"seed{info['seed']}.json")
                with open(path, "w") as f:
                    json.dump(dump, f, indent=1, default=str)
                failures.append(dict(structural=structural,
                                     runtime=runtime, errors=errs,
                                     attack=info["attack"],
                                     seed=info["seed"], minidump=path))
                print(json.dumps(failures[-1]), flush=True)
            if trials % 10 == 0:
                print(f"[fuzz] {trials} adversary trials "
                      f"({adv_stats['windows']} windows, "
                      f"{adv_stats['partition']} partitions, "
                      f"{adv_stats['link']} link matrices), "
                      f"{len(failures)} failures", file=sys.stderr,
                      flush=True)
            continue
        if SCENARIO:
            # Heterogeneous-fleet mode: the structural axis is SHAPES
            # only (delay/commit-chain/byz/drop are per-slot data — the
            # executable-count collapse under test); every trial fuzzes
            # SCENARIO_SLOTS independent scenarios at once.
            sk = rng.randrange(len(SCENARIO_STRUCTURAL))
            structural = SCENARIO_STRUCTURAL[sk]
            specs, slot_errs = scenario_trial(structural, rng)
            trials += 1
            shapes_used.add((sk, 1))
            for spec in specs:
                if spec.byz_kind != "honest":
                    byz_trials["byz_" + spec.byz_kind] += 1
            if slot_errs:
                plane = [s.to_dict() for s in specs]
                dump = dict(structural=structural, plane=plane,
                            slot_errors={str(k): v
                                         for k, v in slot_errs.items()})
                path = (f"FUZZ_MINIDUMP_SCEN_{len(failures):04d}_"
                        f"seed{specs[0].seed}.json")
                with open(path, "w") as f:
                    json.dump(dump, f, indent=1, default=str)
                failures.append(dict(structural=structural, plane=plane,
                                     errors=[e for v in slot_errs.values()
                                             for e in v],
                                     minidump=path))
                print(json.dumps(failures[-1]), flush=True)
            if trials % 10 == 0:
                print(f"[fuzz] {trials} scenario trials "
                      f"({trials * SCENARIO_SLOTS} slots), "
                      f"{len(failures)} failures", file=sys.stderr,
                      flush=True)
            continue
        sk = rng.randrange(len(STRUCTURAL))
        structural = STRUCTURAL[sk]
        runtime = dict(rng.choice(DELAYS))
        runtime["drop_prob"] = rng.choice([0.0, 0.0, 0.02, 0.05, 0.15])
        runtime["max_clock"] = rng.choice([400, 800, 1500])
        macro_k = rng.choice(MACRO_KS) if MACRO else 1
        p = SimParams(**structural, **runtime, packed=PACKED,
                      macro_k=macro_k)
        seed = rng.randrange(2**31)
        shapes_used.add((sk, macro_k))
        macro_trials[macro_k] = macro_trials.get(macro_k, 0) + 1
        # Byzantine leg (~40% of trials): up to f = floor((n-1)/3) nodes
        # get a random attacker kind; masks are runtime data (SimState),
        # so this shares the honest trials' executables.
        byz = None
        n = p.n_nodes
        f_max = (n - 1) // 3
        if f_max and rng.random() < 0.4:
            kind = rng.choice(["byz_equivocate", "byz_silent", "byz_forge_qc"])
            mask = [False] * n
            for a in rng.sample(range(n), rng.randrange(1, f_max + 1)):
                mask[a] = True
            byz = {kind: mask}
            byz_trials[kind] += 1
        errs = one_trial(p, seed, byz)
        trials += 1
        if errs:
            minidump = write_minidump(p, seed, structural, runtime, byz,
                                      errs, len(failures))
            failures.append(dict(structural=structural, runtime=runtime,
                                 macro_k=macro_k, seed=seed, byz=byz,
                                 errors=errs, minidump=minidump))
            print(json.dumps(failures[-1]), flush=True)
        if trials % 10 == 0:
            print(f"[fuzz] {trials} trials, {len(shapes_used)} shapes, "
                  f"{len(failures)} failures", file=sys.stderr, flush=True)
    out = dict(trials=trials, byz_trials=byz_trials, packed=PACKED,
               macro=MACRO, scenario=SCENARIO, adversary=ADVERSARY,
               scenario_slots=(SCENARIO_SLOTS if SCENARIO else 0),
               slots_checked=(trials * SCENARIO_SLOTS if SCENARIO else 0),
               adversary_stats=(dict(adv_stats, adv_windows=ADV_WINDOWS)
                                if ADVERSARY else None),
               macro_trials={str(k): v for k, v in
                             sorted(macro_trials.items())},
               structural_shapes=len(shapes_used), failures=failures)
    artifact = ("FUZZ_PARITY_r17_adversary.json" if ADVERSARY
                else "FUZZ_PARITY_r14_scenario.json" if SCENARIO
                else "FUZZ_PARITY_r11_macro.json" if MACRO
                else "FUZZ_PARITY_r06_packed.json" if PACKED
                else "FUZZ_PARITY_r05.json")
    with open(artifact, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: v for k, v in out.items() if k != "failures"}
                     | {"n_failures": len(failures)}))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
