"""The CI census/audit budgets — single source of truth.

Every ``--assert-*`` regression gate reads its budget from here: the four
kernel-census fusion budgets and the tier-1 dot floor used to live as env
defaults in ``scripts/ci_tier1.sh`` AND as numbers restated in comments and
flag help — drift between the copies was only a matter of time.  Now:

* ``scripts/ci_tier1.sh`` materializes them with
  ``eval "$(python scripts/budgets.py --sh)"`` (caller-exported overrides
  win — the emitted lines use ``${VAR:-default}``);
* ``scripts/kernel_census.py --assert-budgets`` applies all four census
  budgets directly;
* the source lint (audit/source_lint.py rule S4) flags any budget value
  reappearing as a literal on a budget-ish line elsewhere in scripts/.

Provenance of the values (ROUND-11 RE-BASELINE): the container's
jaxlib/XLA update changed both the optimizer's fusion decisions AND the
HLO text format — tuple-typed computation-header params and
``/*index=N*/`` type comments defeated the old census parser, which had
been undercounting (the recorded 326/205/214/226 counts of rounds 6-10
are not reproducible on this toolchain; the graphs themselves are
unchanged — the graph audit's jaxpr signatures and R1 waived-site pins
carried over exactly).  kernel_census.py's parser was repaired and every
budget re-measured (KERNEL_CENSUS_r11.json, n=4/B=2048 CPU-lowering
proxy, jax 0.4.37 / jaxlib 0.4.36 container); relative claims
(telemetry small, K-macro amortization ~K-fold) hold on both
toolchains.

* ``census_off`` 1070       — tpu_shape top fusions 1000 + ~7% headroom.
* ``census_telemetry`` 1090 — tpu_shape_telemetry 1018 (+18 for plane +
  flight recorder) + the same headroom.
* ``census_watchdog`` 1080  — tpu_shape_watchdog 1006 (+6; the round-9
  "zero-fusion watchdog" was a property of the old XLA's fusion choices
  — on this toolchain the detectors cost 6 top-level fusion sites).
* ``census_sharded`` 1160   — per-shard program 1081 (tpu_shape +
  scan/pack/halt-digest overhead) + headroom.
* ``census_ring_k4`` 1170 / ``census_ring_k16`` 1170 — the device
  -dispatch ring programs (SimParams.wrap="device"; parallel/sharded.py
  round 19): per-shard 1091 top fusions at BOTH K=4 and K=16 (round-19
  container) — the in-graph `lax.while_loop` chunk-retirement body is
  ONE chunk, so the dispatched program costs +10 fusion sites over the
  sharded base 1081 (ring dynamic_update_slice + halt predicate + cap
  compare) and stays ~flat in K while retiring up to K chunks per
  dispatch; + ~7% headroom like the others.  A ring budget ballooning
  toward K x census_sharded means XLA started unrolling the retirement
  loop — the amortization's compile-size guarantee died.
* ``census_scenario`` 1140 — the per-slot scenario-plane graph
  (SimParams.scenario; serve/scenario.py): tpu_shape_scenario 1068 vs
  1047 off on the round-14 container (the same tree measures off at
  1047, within the 1070 budget — residual toolchain jitter vs the
  round-11 1000, not a graph change: the graph audit's off-graph
  scenario arm proves zero sc-leaf eqns) — +21 fusion sites for the
  traced per-slot delay-table reads and the 2-vs-3-chain commit
  selects, ~7% headroom like the others.  Scenario OFF stays under
  ``census_off`` exactly (zero-width leaves compile out).
* ``census_adversary`` 1080 / ``census_adversary_lane`` 1200 — the
  adversary-plane graphs (SimParams.adversary; adversary/):
  tpu_shape_adversary 1009 vs 1000 off (+9 fusion sites for the windowed
  attack-schedule decode, per-link delay adds, and partition cuts —
  KERNEL_CENSUS_r17.json) and the LANE engine's adversary window step
  1121 (the per-link horizon derivation rides existing fusions; the
  lane flavor had no prior census — this is its first recorded value),
  each + ~7% headroom.  Adversary OFF stays under ``census_off``
  exactly (zero-width leaves compile out; the graph audit's R6
  adversary arm is the static twin).
* ``census_k4`` 1090 / ``census_k16`` 1090 — the K-event macro-step
  programs (SimParams.macro_k; sim/simulator.py macro_step): 1018 top
  fusions at BOTH K=4 and K=16 — the rolled inner scan's body is one
  step, so the dispatched program stays ~flat in K while retiring K
  events (254.5 fusions/event at K=4, 63.6 at K=16 vs 1000 at K=1 =
  15.7x amortization; the >=3x round-11 acceptance gate).  A K budget
  ballooning toward K x census_off means the amortization silently died.
* ``tier1_min_dots`` 39     — the seed suite's dot count at the 870 s
  timeout; PR baselines since run 49-59 (see CHANGES.md).
* ``bench_sentinel_tol_pct`` 100 — the perf-regression sentinel's noise
  tolerance (scripts/perf_sentinel.py): a rung regresses only past
  (1 + pct/100) x its rolling-median baseline, i.e. 2x at the default.
  Round-18 provenance: shared-CI CPU rung medians (median-of-3 reps)
  jitter up to ~40-60% run-over-run on the micro shapes, so a 2x gate
  catches a real dispatch/compile regression while never tripping on
  scheduler noise; tighten per-run via BENCH_SENTINEL_TOL_PCT once the
  runner hardware is quieter.

``DONATION`` (round 16) pins the donation/aliasing verifier's expected
per-flavor donated-leaf counts (audit/donation_lint.py rule D1) — exact
equalities, not ceilings; provenance inline below.

Usage:
    python scripts/budgets.py            # print the table
    python scripts/budgets.py --sh       # shell-eval'able defaults
    python scripts/budgets.py --json     # machine-readable
"""

import json
import sys

BUDGETS = {
    "census_off": 1070,
    "census_telemetry": 1090,
    "census_watchdog": 1080,
    "census_sharded": 1160,
    "census_ring_k4": 1170,
    "census_ring_k16": 1170,
    "census_k4": 1090,
    "census_k16": 1090,
    "census_scenario": 1140,
    "census_adversary": 1080,
    "census_adversary_lane": 1200,
    "tier1_min_dots": 39,
    "bench_sentinel_tol_pct": 100,
}

#: Expected DONATED input-leaf count per runner flavor — the D1 pin
#: (audit/donation_lint.py reads this; round-16 measurement).  A donation
#: map is a leaf-count property of (donate_argnums x pytree structure),
#: independent of shapes, so these are exact equalities, not budgets:
#: any drift (a state leaf added/removed, a donate_argnums change, a
#: jit that silently stopped donating) is a gated diff, reviewed next to
#: the dedupe_buffers call-site audit — never a silent rebaseline.
#: Provenance: engine states flatten to 114 leaves (PSimState 112) since
#: round 17 added the four adversary-plane leaves
#: (adv_sched/adv_link/adv_group/adv_heal — zero-width when the plane is
#: off, donated like every other state leaf; the round-16 pins were
#: 110/108); the serial/lane runners donate exactly the state argument
#: (tables and the lane lookahead scalar are host-reused), the sharded
#: runner's ONLY input is the donated state (the ring flavor adds the
#: host's chunk-budget cap scalar, read-only — never donated),
#: install_rows donates the
#: resident state but never the admission mask/donor, and the checkify
#: sanitizer build donates NOTHING (callers hand it externally-held
#: states with no dedupe obligation).
DONATION = {
    "serial/run": 114,
    "serial/digest": 114,
    "serial/telemetry": 114,
    "serial/scenario": 114,
    "lane/digest": 112,
    "sharded/digest": 114,
    "sharded/ring": 114,
    "sharded/scenario": 114,
    "serve/install": 114,
    "sanitize/serial": 0,
}

#: The shell variable each budget materializes as (ci_tier1.sh contract).
SH_VARS = {
    "census_off": "CENSUS_BUDGET",
    "census_telemetry": "TELEMETRY_CENSUS_BUDGET",
    "census_watchdog": "WATCHDOG_CENSUS_BUDGET",
    "census_sharded": "SHARDED_CENSUS_BUDGET",
    "census_ring_k4": "RING_K4_CENSUS_BUDGET",
    "census_ring_k16": "RING_K16_CENSUS_BUDGET",
    "census_k4": "K4_CENSUS_BUDGET",
    "census_k16": "K16_CENSUS_BUDGET",
    "census_scenario": "SCENARIO_CENSUS_BUDGET",
    "census_adversary": "ADVERSARY_CENSUS_BUDGET",
    "census_adversary_lane": "ADVERSARY_LANE_CENSUS_BUDGET",
    "tier1_min_dots": "TIER1_MIN_DOTS",
    "bench_sentinel_tol_pct": "BENCH_SENTINEL_TOL_PCT",
}


def main(argv) -> int:
    if "--sh" in argv:
        # ${VAR:-default}: a caller-exported override survives the eval.
        for key, var in SH_VARS.items():
            print(f'{var}="${{{var}:-{BUDGETS[key]}}}"')
        return 0
    if "--json" in argv:
        print(json.dumps(BUDGETS))
        return 0
    for key, val in BUDGETS.items():
        print(f"{key:18s} {val:4d}  (${SH_VARS[key]})")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
