"""The CI census/audit budgets — single source of truth.

Every ``--assert-*`` regression gate reads its budget from here: the four
kernel-census fusion budgets and the tier-1 dot floor used to live as env
defaults in ``scripts/ci_tier1.sh`` AND as numbers restated in comments and
flag help — drift between the copies was only a matter of time.  Now:

* ``scripts/ci_tier1.sh`` materializes them with
  ``eval "$(python scripts/budgets.py --sh)"`` (caller-exported overrides
  win — the emitted lines use ``${VAR:-default}``);
* ``scripts/kernel_census.py --assert-budgets`` applies all four census
  budgets directly;
* the source lint (audit/source_lint.py rule S4) flags any budget value
  reappearing as a literal on a budget-ish line elsewhere in scripts/.

Provenance of the values:

* ``census_off`` 220       — tpu_shape top fusions 205 (KERNEL_CENSUS_r06,
  n=4/B=2048 CPU-lowering proxy) + ~7% headroom.
* ``census_telemetry`` 230 — tpu_shape_telemetry 214 (KERNEL_CENSUS_r07:
  +9 fusions for plane + flight recorder) + the same headroom.
* ``census_watchdog`` 220  — the watchdog measured ZERO top-level fusion
  cost (KERNEL_CENSUS_r09: 205 == off), so its ON budget IS the off
  budget: a regression that makes disabled-quality detectors cost kernels
  fails even if the off graph stays clean.
* ``census_sharded`` 238   — per-shard program 222-226 (205 + scan/pack/
  halt-digest overhead; KERNEL_CENSUS_r09) + headroom.
* ``tier1_min_dots`` 39    — the seed suite's dot count at the 870 s
  timeout; PR baselines since run 49-59 (see CHANGES.md).

Usage:
    python scripts/budgets.py            # print the table
    python scripts/budgets.py --sh       # shell-eval'able defaults
    python scripts/budgets.py --json     # machine-readable
"""

import json
import sys

BUDGETS = {
    "census_off": 220,
    "census_telemetry": 230,
    "census_watchdog": 220,
    "census_sharded": 238,
    "tier1_min_dots": 39,
}

#: The shell variable each budget materializes as (ci_tier1.sh contract).
SH_VARS = {
    "census_off": "CENSUS_BUDGET",
    "census_telemetry": "TELEMETRY_CENSUS_BUDGET",
    "census_watchdog": "WATCHDOG_CENSUS_BUDGET",
    "census_sharded": "SHARDED_CENSUS_BUDGET",
    "tier1_min_dots": "TIER1_MIN_DOTS",
}


def main(argv) -> int:
    if "--sh" in argv:
        # ${VAR:-default}: a caller-exported override survives the eval.
        for key, var in SH_VARS.items():
            print(f'{var}="${{{var}:-{BUDGETS[key]}}}"')
        return 0
    if "--json" in argv:
        print(json.dumps(BUDGETS))
        return 0
    for key, val in BUDGETS.items():
        print(f"{key:18s} {val:4d}  (${SH_VARS[key]})")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
