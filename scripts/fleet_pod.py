"""Multi-process pod ladder: the fleet bench over 1/2/4 REAL OS processes.

The dp fleet ladder (BENCH_FLEET=1) scales over one process's virtual
devices; this harness scales over PROCESSES — each rung is a genuine
``jax.distributed`` job (loopback coordinator, gloo CPU collectives, one
device per process) running the production pipelined ``run_sharded``
loop, with per-host digest streams, per-host runtime-ledger spans, and
per-host checkpoint-shard egress: the full pod-runtime story, CPU-
emulated until the TPU tunnel revives.

Honest caveat, like MULTICHIP_FLEET_r08: the P processes timeshare this
host's cores, so the emulated efficiency curve decays ~1/P by
construction — the artifact certifies the multi-process HARNESS (the
bootstrap wiring, the per-host egress discipline, the one-digest-per-
chunk-per-process poll contract, the ledger attribution), not ICI
scaling.  Real numbers come from rerunning on a pod slice (ROADMAP).

Knobs: BENCH_POD_PROCS (default "1,2,4"), BENCH_POD_B (instances per
process), BENCH_POD_STEPS (macro-steps per chunk), BENCH_POD_REPS
(minimum dispatched chunks per rung), BENCH_POD_OUT (artifact path),
BENCH_POD_AOT_DIR (the per-topology AOT store the rungs warm — on
multi-process CPU the persistent XLA cache cannot cross processes: jax
hashes the device assignment into the cache key on every platform but
GPU, so process 0 hits and every other process recompiles; the AOT
store, keyed on global device count, is the fix AND the pod
ship-the-store workflow).  Run directly or via ``BENCH_POD=1 python
bench.py``.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PROCS_ENV = "BENCH_POD_PROCS"
B_ENV = "BENCH_POD_B"
STEPS_ENV = "BENCH_POD_STEPS"
REPS_ENV = "BENCH_POD_REPS"
OUT_ENV = "BENCH_POD_OUT"
AOT_DIR_ENV = "BENCH_POD_AOT_DIR"

DEFAULT_OUT = "MULTIHOST_FLEET_r15.json"
#: Persistent across runs (like /tmp/jax_cache): rung P's first run
#: exports, later runs aot-hit in every process.
DEFAULT_AOT_DIR = "/tmp/librabft_aot_pod"


def _rung(procs: int, b_per: int, chunk: int, reps: int, workdir: str
          ) -> dict:
    from librabft_simulator_tpu.distributed import bootstrap
    from librabft_simulator_tpu.telemetry import ledger as tledger

    params_kw = {"n_nodes": 4, "delay_kind": "uniform", "queue_cap": 32,
                 "epoch_handoff": False, "max_clock": 2**30}
    out_dir = os.path.join(workdir, f"pod-{procs}")
    results = bootstrap.local_cluster(
        procs, "librabft_simulator_tpu.distributed.workers:fleet_run",
        {"params_kw": params_kw, "engine": "serial", "b": b_per * procs,
         "chunk": chunk, "num_steps": chunk * reps, "reps_floor": reps,
         "out_dir": out_dir},
        timeout_s=1800, workdir=os.path.join(workdir, f"cluster-{procs}"),
        ledger=True,
        env_extra={
            "LIBRABFT_AOT_DIR": os.environ.get(AOT_DIR_ENV,
                                               "") or DEFAULT_AOT_DIR,
            "LIBRABFT_AOT_WRITE": "1",
        })
    hosts = []
    for res in results:
        pid = res["process_id"]
        ledger_path = os.path.join(workdir, f"cluster-{procs}",
                                   f"ledger-p{pid}.ndjson")
        pipe = {}
        compiles = []
        try:
            rows = tledger.read_ndjson(ledger_path)
            runs = sorted({r["run"] for r in rows
                           if r.get("kind") == "span"
                           and r.get("run") is not None})
            pipe = (tledger.pipeline_stats(rows, run=runs[-1])
                    if runs else {})
            compiles = [
                {k: e.get(k) for k in ("engine", "compile_s",
                                       "first_call_s", "cache",
                                       "aot_load_s")}
                for e in rows if e.get("kind") == "compile"]
        except (OSError, ValueError):
            pass
        # Steady-state ev/s from the digest rows (chunk 0 carries the
        # compile/load; the digest's events counter is fleet-global).
        drows = res.get("digest_rows") or []
        ev_per_s = None
        if len(drows) >= 2:
            # t_s is not in digest_rows (deterministic columns only);
            # fall back to the ledger's chunk spans for the window.
            span_rows = pipe.get("rows") or []
            steady = [r for r in span_rows if r["chunk"] >= 1]
            dt = sum(r["dispatch_s"] + r["poll_s"] for r in steady)
            dev = drows[-1]["events"] - drows[0]["events"]
            ev_per_s = round(dev / dt, 1) if dt > 0 else None
        hosts.append({
            "process_id": pid,
            "spans": res["spans"],
            "chunks_dispatched": res["chunks_dispatched"],
            "chunks_polled": res["chunks_polled"],
            "poll_contract_ok": (
                res["poll_shapes_ok"]
                and res["chunks_polled"] == res["chunks_dispatched"]),
            "elapsed_s": res["elapsed_s"],
            "events_per_sec_steady": ev_per_s,
            "time_to_first_chunk_s": pipe.get("time_to_first_chunk_s"),
            "overlap_fraction": pipe.get("overlap_fraction"),
            "bubble_count": pipe.get("bubble_count"),
            "dispatch_poll_rows": pipe.get("rows"),
            "compiles": compiles,
        })
    final = results[0].get("final_digest") or {}
    # Fleet throughput: the digest's events slot is psum-reduced — any
    # host's steady-state number IS the fleet aggregate.
    agg = next((h["events_per_sec_steady"] for h in hosts
                if h["events_per_sec_steady"]), None)
    return {
        "processes": procs,
        "instances": b_per * procs,
        "per_process_instances": b_per,
        "chunk_steps": chunk,
        "chunks": results[0]["chunks_polled"],
        "events_total": final.get("events"),
        "events_per_sec": agg,
        "poll_contract_ok": all(h["poll_contract_ok"] for h in hosts),
        "digest_streams_identical": all(
            r["digest_rows"] == results[0]["digest_rows"]
            for r in results),
        "per_host": hosts,
    }


def run_ladder(out_path: str | None = None) -> dict:
    import tempfile

    try:
        rungs = [int(x) for x in
                 os.environ.get(PROCS_ENV, "1,2,4").split(",")
                 if x.strip()]
    except ValueError:
        print("fleet_pod: ignoring malformed BENCH_POD_PROCS",
              file=sys.stderr)
        rungs = [1, 2, 4]
    b_per = int(os.environ.get(B_ENV, "64"))
    chunk = int(os.environ.get(STEPS_ENV, "16"))
    reps = int(os.environ.get(REPS_ENV, "4"))
    out_path = out_path or os.environ.get(OUT_ENV, "") or DEFAULT_OUT
    workdir = tempfile.mkdtemp(prefix="librabft_pod_")
    rows, failures = [], {}
    for procs in rungs:
        try:
            row = _rung(procs, b_per, chunk, reps, workdir)
            rows.append(row)
            print(json.dumps({k: row[k] for k in (
                "processes", "instances", "events_per_sec",
                "poll_contract_ok")}), file=sys.stderr, flush=True)
        except Exception as e:  # noqa: BLE001 - ladder rung boundary
            failures[procs] = f"{type(e).__name__}: {e}"[:500]
            print(f"fleet_pod: rung P={procs} failed "
                  f"({failures[procs][:200]})", file=sys.stderr)
    base = next((r["events_per_sec"] for r in rows
                 if r["processes"] == 1), None)
    for r in rows:
        r["scaling_efficiency"] = (
            round(r["events_per_sec"] / (r["processes"] * base), 3)
            if base and r["events_per_sec"] else None)
    art = {
        "kind": "multihost_fleet_ladder",
        "platform": "cpu",
        "emulated": True,
        "host_cores": os.cpu_count(),
        "note": "each rung is a REAL multi-process jax.distributed job "
                "(loopback coordinator, gloo collectives, 1 device per "
                "process) running the production double-buffered "
                "run_sharded loop with per-host digest streams, "
                "per-host ledger spans, and per-host checkpoint-shard "
                "egress.  The P processes timeshare this host's cores, "
                "so the emulated efficiency decays ~1/P by construction "
                "— the artifact certifies the multi-process harness and "
                "the per-process one-[13]-digest-per-chunk poll "
                "contract, not ICI scaling; rerun on a pod slice "
                "(ROADMAP tunnel checklist).  Multi-process CPU cannot "
                "share the persistent XLA cache across processes (the "
                "device assignment is hashed into the cache key on "
                "non-GPU platforms), so the rungs warm the AOT "
                "executable store instead — the pod "
                "ship-the-store-to-every-host workflow.",
        "rungs": rows,
        "failures": failures,
    }
    with open(out_path, "w") as f:
        json.dump(art, f, indent=1)
    print(f"fleet_pod: wrote {out_path} "
          f"({len(rows)} rungs, {len(failures)} failures)",
          file=sys.stderr)
    head = {
        "metric": "multihost_fleet_events_per_sec",
        "value": rows[-1]["events_per_sec"] if rows else 0.0,
        "unit": "events/sec",
        "processes": rows[-1]["processes"] if rows else 0,
        "efficiency_curve": {str(r["processes"]): r["scaling_efficiency"]
                             for r in rows},
        "poll_contract_ok": all(r["poll_contract_ok"] for r in rows),
        "artifact": out_path,
    }
    print(json.dumps(head))
    return art


def main(argv=None) -> int:
    art = run_ladder()
    return 1 if (art["failures"] or not art["rungs"]) else 0


if __name__ == "__main__":
    sys.exit(main())
