"""Find the largest working fleet size on the tunneled TPU chip.

The chip faults (UNAVAILABLE kernel fault) at B=32768 on the serial engine;
this script climbs a ladder of batch sizes, timing each rung that works and
recording each rung that faults, so the round's TPU measurement is the best
the device can actually do.  Emits one JSON line per rung and a summary file
(BENCH_TPU_LADDER_r05.json).

Usage: python scripts/tpu_ladder.py [serial|parallel] [B ...]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from librabft_simulator_tpu.utils.rlimit import raise_stack_limit

raise_stack_limit()

import jax  # noqa: E402
import numpy as np  # noqa: E402

from librabft_simulator_tpu.utils.cache import setup_compile_cache  # noqa: E402

setup_compile_cache()


def rung(engine_name: str, batch: int, chunk: int, reps: int) -> dict:
    from librabft_simulator_tpu.core.types import SimParams
    from librabft_simulator_tpu.sim import parallel_sim, simulator
    from librabft_simulator_tpu.sim.simulator import dedupe_buffers

    engine = parallel_sim if engine_name == "parallel" else simulator
    p = SimParams(n_nodes=4, delay_kind="uniform", max_clock=2**30,
                  epoch_handoff=False, queue_cap=32,
                  unroll=os.environ.get("LADDER_UNROLL", "0") == "1")
    out = {"engine": engine_name, "instances": batch, "chunk": chunk,
           "reps": reps, "unroll": p.unroll}
    try:
        seeds = np.arange(batch, dtype=np.uint32)
        st = engine.init_batch(p, seeds)
        st = dedupe_buffers(st)
        run = engine.make_run_fn(p, chunk)
        t0 = time.perf_counter()
        st = run(st)
        jax.block_until_ready(st)
        out["compile_s"] = round(time.perf_counter() - t0, 1)
        cur0 = jax.device_get(st.store.current_round)
        e0 = int(np.sum(jax.device_get(st.n_events)))
        t0 = time.perf_counter()
        for _ in range(reps):
            st = run(st)
        jax.block_until_ready(st)
        dt = time.perf_counter() - t0
        cur1 = jax.device_get(st.store.current_round)
        e1 = int(np.sum(jax.device_get(st.n_events)))
        rounds = int(np.sum(np.max(cur1, -1)) - np.sum(np.max(cur0, -1)))
        # Fidelity guards matching bench.py::_time_engine: overflow-loss
        # accounting, and the epoch_handoff=False premise checked.
        lost_field = (st.n_queue_full if hasattr(st, "n_queue_full")
                      else st.n_inbox_full)
        lost = int(np.sum(jax.device_get(lost_field)))
        sent = int(np.sum(jax.device_get(st.n_msgs_sent)))
        max_epoch = int(np.max(jax.device_get(st.store.epoch_id)))
        assert max_epoch == 0, (
            f"ladder crossed an epoch boundary (max epoch {max_epoch}) "
            "with epoch_handoff=False")
        out.update(ok=True, elapsed_s=round(dt, 3),
                   rounds_per_sec=round(rounds / dt, 1),
                   events_per_sec=round((e1 - e0) / dt, 1),
                   overflow_frac=round(lost / max(sent + lost, 1), 4))
    except Exception as e:  # noqa: BLE001 - record the fault and keep going
        out.update(ok=False, error=f"{type(e).__name__}: {e}"[:300])
    return out


def main() -> None:
    engine = sys.argv[1] if len(sys.argv) > 1 else "serial"
    ladder = ([int(x) for x in sys.argv[2:]]
              or [2048, 4096, 8192, 16384, 24576, 32768])
    chunk = int(os.environ.get("LADDER_CHUNK", "64"))
    reps = int(os.environ.get("LADDER_REPS", "2"))
    rows = []
    for b in ladder:
        r = rung(engine, b, chunk, reps)
        r["platform"] = jax.devices()[0].platform
        print(json.dumps(r), flush=True)
        rows.append(r)
        if not r["ok"]:
            break  # a faulted device often wedges the session; stop clean
    suffix = "" if engine == "serial" else f"_{engine}"
    if rows and rows[0].get("unroll"):
        suffix += "_unroll"
    with open(f"BENCH_TPU_LADDER{suffix}_r05.json", "w") as f:
        json.dump({"ladder": rows}, f, indent=1)


if __name__ == "__main__":
    main()
