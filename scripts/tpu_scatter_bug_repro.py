"""Minimal repro of the axon-TPU batched scalar-scatter miscompile.

A vmapped scalar scatter into a small trailing dim, followed by a select,
returns wrong rows for a data-dependent ~18% of a B=2048 batch (B=64 is
fine).  int8 casting and folding the condition into a dropped-OOB scatter
index do NOT help; the one-hot ``jnp.where`` form is correct — which is why
the whole engine writes scalar slots through ``utils/xops.wset``.

Found round 5: the serial engine's vote table (`vt_valid`, bool [B, 4])
was silently corrupted at bench scale — 21 total commits instead of 34,144
at B=2048 x 192 events — while every B=64 parity check passed.

Run on a machine with the TPU tunnel up: ``python scripts/tpu_scatter_bug_repro.py``.
Prints one JSON line per form; "bug_present": true means the scatter form
still disagrees with ground truth on this stack.
"""

from __future__ import annotations

import json
import sys

import jax
import jax.numpy as jnp
import numpy as np

B, N = 2048, 4


def main() -> None:
    rng = np.random.default_rng(0)
    base = jnp.asarray(rng.random((B, N)) < 0.3)
    idx = jnp.asarray(rng.integers(0, N, B), jnp.int32)
    ok = jnp.asarray(rng.random(B) < 0.5)

    gt = np.array(base)
    for i in range(B):
        if ok[i]:
            gt[i, idx[i]] = True

    def scatter_select(b, a, o):
        return jnp.where(o, b.at[a].set(True), b)

    def where_onehot(b, a, o):
        return jnp.where((jnp.arange(N) == a) & o, True, b)

    dev = jax.devices()[0]
    print(json.dumps({"platform": dev.platform, "B": B, "N": N}))
    if dev.platform == "cpu":
        print(json.dumps({"error": "needs the TPU backend"}))
        sys.exit(1)
    for name, fn in (("scatter_select", scatter_select),
                     ("where_onehot", where_onehot)):
        out = np.asarray(jax.jit(jax.vmap(fn))(base, idx, ok))
        n_bad = int(np.sum((out != gt).any(axis=1)))
        print(json.dumps({"form": name, "bad_rows": n_bad,
                          "bug_present": n_bad > 0}))


if __name__ == "__main__":
    main()
