"""A/B the batched-scatter vs dense-one-hot forms on the live backend.

The serial engine's step is dominated by per-instance dynamic-index ops
(``x.at[i].set(v)`` / ``x[i]`` under ``vmap`` over B instances).  On CPU the
scatters fuse in place and dense replacements measured SLOWER (PERF_NOTES);
on TPU batched scatters lower to serialized update loops.  This script times
both forms for the step's three characteristic shapes so the engine's
``dense_updates`` auto mode is set by measurement, not folklore:

  - store-table write:   [B, W=16, V=2] scatter at slot = round % W
  - node-state write:    [B, N=4, F=8] row update at node a
  - queue insert:        [B, CM=32] x C=9 candidate scatter

Each form runs inside one jitted ``lax.scan`` of length ITERS so dispatch
overhead is amortized and XLA sees the op in a loop (the in-graph regime
PERF_NOTES says is the only one that decides).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

I32 = jnp.int32
B = int(os.environ.get("AB_B", "8192"))
ITERS = int(os.environ.get("AB_ITERS", "64"))


def timed(name, make_scan, *args):
    f = jax.jit(make_scan)
    t0 = time.perf_counter()
    out = jax.block_until_ready(f(*args))
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        out = jax.block_until_ready(f(*args))
    dt = (time.perf_counter() - t0) / reps
    print(json.dumps({"case": name, "per_scan_ms": round(dt * 1e3, 2),
                      "per_iter_us": round(dt / ITERS * 1e6, 1),
                      "compile_s": round(compile_s, 1)}), flush=True)
    return out


def scan(body):
    def run(x, idx):
        def f(carry, i):
            return body(carry, idx, i), ()
        return jax.lax.scan(f, x, jnp.arange(ITERS))[0]
    return run


def main():
    print(json.dumps({"platform": jax.devices()[0].platform, "B": B,
                      "iters": ITERS}), flush=True)
    key = np.random.default_rng(0)

    # ---- store-table write [B, 16, 2]
    x = jnp.asarray(key.integers(0, 100, (B, 16, 2)), I32)
    idx = jnp.asarray(key.integers(0, 16, (B,)), I32)

    def sc_body(c, idx, i):
        v = c[jnp.arange(B), idx, 0] + i
        return jax.vmap(lambda cx, ix, vx: cx.at[ix, 0].set(vx))(c, idx, v)

    def dn_body(c, idx, i):
        hot = (jnp.arange(16)[None] == idx[:, None])  # [B, 16]
        hot = hot[..., None] & (jnp.arange(2)[None, None] == 0)  # [B, 16, 2]
        v = jnp.sum(jnp.where(hot, c, 0), axis=(1, 2)) + i  # == c[b, idx, 0]
        return jnp.where(hot, v[:, None, None], c)

    timed("store_scatter", scan(sc_body), x, idx)
    timed("store_dense", scan(dn_body), x, idx)

    # ---- node-row write [B, 4, 8]
    xn = jnp.asarray(key.integers(0, 100, (B, 4, 8)), I32)
    a = jnp.asarray(key.integers(0, 4, (B,)), I32)

    def nsc(c, a, i):
        row = jax.vmap(lambda cx, ax: cx[ax])(c, a) + i
        return jax.vmap(lambda cx, ax, rx: cx.at[ax].set(rx))(c, a, row)

    def ndn(c, a, i):
        hot = (jnp.arange(4)[None] == a[:, None])  # [B, 4]
        row = jnp.sum(jnp.where(hot[..., None], c, 0), axis=1) + i
        return jnp.where(hot[..., None], row[:, None], c)

    timed("node_scatter", scan(nsc), xn, a)
    timed("node_dense", scan(ndn), xn, a)

    # ---- queue insert: C=9 candidates into [B, 32]
    q = jnp.asarray(key.integers(0, 100, (B, 32)), I32)
    tgt = jnp.asarray(key.integers(0, 33, (B, 9)), I32)  # 32 == drop sentinel

    def qsc(c, tgt, i):
        vals = jnp.broadcast_to(i, (B, 9))
        return jax.vmap(lambda cx, tx, vx: cx.at[tx].set(vx, mode="drop"))(
            c, tgt, vals)

    def qdn(c, tgt, i):
        hot = (tgt[..., None] == jnp.arange(32)[None, None])  # [B, 9, 32]
        any_hot = jnp.any(hot, axis=1)
        return jnp.where(any_hot, i, c)

    timed("queue_scatter", scan(qsc), q, tgt)
    timed("queue_dense", scan(qdn), q, tgt)


if __name__ == "__main__":
    main()
