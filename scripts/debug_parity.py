"""Lockstep parity debugger: step JAX sim and oracle together, print first
divergence in observable state."""

import sys

sys.path.insert(0, ".")
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import numpy as np

jax.config.update("jax_platforms", "cpu")

from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.oracle.sim import OracleSim
from librabft_simulator_tpu.sim import simulator as S


def snap_jax(st):
    g = lambda x: np.asarray(jax.device_get(x))
    return dict(
        clock=int(st.clock), stamp=int(st.stamp_ctr), ev=int(st.n_events),
        halted=bool(st.halted),
        cur=g(st.store.current_round).tolist(),
        hqc=g(st.store.hqc_round).tolist(),
        htc=g(st.store.htc_round).tolist(),
        hcr=g(st.store.hcr).tolist(),
        cc=g(st.ctx.commit_count).tolist(),
        lvr=g(st.node.latest_voted_round).tolist(),
        lock=g(st.node.locked_round).tolist(),
        tt=g(st.timer_time).tolist(),
        ts=g(st.timer_stamp).tolist(),
        qvalid=int(g(st.queue.valid).sum()),
        qtimes=sorted(g(st.queue.time)[g(st.queue.valid)].tolist()),
        qkinds=sorted(g(st.queue.kind)[g(st.queue.valid)].tolist()),
        qstamps=sorted(g(st.queue.stamp)[g(st.queue.valid)].tolist()),
        sent=int(st.n_msgs_sent), dropped=int(st.n_msgs_dropped),
        full=int(st.n_queue_full),
        pm_round=g(st.pm.active_round).tolist(),
    )


def snap_orc(o):
    live = [m for m in o.queue if m.valid]
    return dict(
        clock=o.clock, stamp=o.stamp_ctr, ev=o.n_events, halted=o.halted,
        cur=[s.current_round for s in o.stores],
        hqc=[s.hqc_round for s in o.stores],
        htc=[s.htc_round for s in o.stores],
        hcr=[s.hcr for s in o.stores],
        cc=[c.commit_count for c in o.ctxs],
        lvr=[n.latest_voted_round for n in o.nxs],
        lock=[n.locked_round for n in o.nxs],
        tt=list(o.timer_time), ts=list(o.timer_stamp),
        qvalid=len(live),
        qtimes=sorted(m.time for m in live),
        qkinds=sorted(m.kind for m in live),
        qstamps=sorted(m.stamp for m in live),
        sent=o.n_msgs_sent, dropped=o.n_msgs_dropped, full=o.n_queue_full,
        pm_round=[pm.active_round for pm in o.pms],
    )


def diff_snaps(a: dict, b: dict) -> dict:
    """Leaf-diff of two observable-state snapshots: {key: (jax, oracle)}
    for every differing leaf (the helper scripts/fuzz_parity.py reuses for
    its first-divergence minidump)."""
    return {k: (a[k], b[k]) for k in a if a[k] != b[k]}


def first_divergence(p: SimParams, seed: int, byz=None, max_ev: int = 5000):
    """Step the jitted serial engine and the oracle in lockstep; return
    ``{"event": i, "diffs": {...}}`` at the first observable divergence,
    None if both run identically to halt, or ``{"exhausted": True,
    "max_ev": N}`` if the event budget ran out first — exhaustion must be
    distinguishable from a clean identical run, or a late divergence reads
    as a passing replay.

    ``byz`` maps init_state Byzantine-mask kwargs (byz_equivocate /
    byz_silent / byz_forge_qc) to [N] bool lists, matching fuzz trials."""
    kw = dict(byz or {})
    # step_fn_partial (not raw S.step): it resolves the 'auto' lowering
    # fields and keeps the SimState-in/SimState-out contract when
    # p.packed is on — the FUZZ_PACKED=1 campaign's shape.
    step = jax.jit(S.step_fn_partial(p))
    st = S.init_state(p, seed, **{k: np.asarray(v) for k, v in kw.items()})
    orc = OracleSim(p, seed, **{k: list(v) for k, v in kw.items()})
    a, b = snap_jax(st), snap_orc(orc)
    if a != b:
        return {"event": 0, "diffs": diff_snaps(a, b)}
    for i in range(max_ev):
        st = step(st)
        orc.step()
        a, b = snap_jax(st), snap_orc(orc)
        if a != b:
            return {"event": i + 1, "diffs": diff_snaps(a, b)}
        if a["halted"]:
            return None
    return {"exhausted": True, "max_ev": max_ev}


def main():
    seed = int(sys.argv[1]) if len(sys.argv) > 1 else 0
    max_ev = int(sys.argv[2]) if len(sys.argv) > 2 else 900
    p = SimParams(n_nodes=3, max_clock=1000)
    div = first_divergence(p, seed, max_ev=max_ev)
    if div is None:
        print("both halted, identical")
        return
    if div.get("exhausted"):
        print(f"no divergence in {max_ev} events (budget exhausted)")
        return
    print(f"DIVERGED at event {div['event']}")
    for k, (a_v, b_v) in div["diffs"].items():
        print(f"  {k}: jax={a_v} oracle={b_v}")


if __name__ == "__main__":
    main()
