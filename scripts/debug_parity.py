"""Lockstep parity debugger: step JAX sim and oracle together, print first
divergence in observable state."""

import functools
import sys

sys.path.insert(0, ".")
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_platforms", "cpu")

from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.oracle.sim import OracleSim
from librabft_simulator_tpu.sim import simulator as S


def snap_jax(st):
    g = lambda x: np.asarray(jax.device_get(x))
    return dict(
        clock=int(st.clock), stamp=int(st.stamp_ctr), ev=int(st.n_events),
        halted=bool(st.halted),
        cur=g(st.store.current_round).tolist(),
        hqc=g(st.store.hqc_round).tolist(),
        htc=g(st.store.htc_round).tolist(),
        hcr=g(st.store.hcr).tolist(),
        cc=g(st.ctx.commit_count).tolist(),
        lvr=g(st.node.latest_voted_round).tolist(),
        lock=g(st.node.locked_round).tolist(),
        tt=g(st.timer_time).tolist(),
        ts=g(st.timer_stamp).tolist(),
        qvalid=int(g(st.queue.valid).sum()),
        qtimes=sorted(g(st.queue.time)[g(st.queue.valid)].tolist()),
        qkinds=sorted(g(st.queue.kind)[g(st.queue.valid)].tolist()),
        qstamps=sorted(g(st.queue.stamp)[g(st.queue.valid)].tolist()),
        sent=int(st.n_msgs_sent), dropped=int(st.n_msgs_dropped),
        full=int(st.n_queue_full),
        pm_round=g(st.pm.active_round).tolist(),
    )


def snap_orc(o):
    live = [m for m in o.queue if m.valid]
    return dict(
        clock=o.clock, stamp=o.stamp_ctr, ev=o.n_events, halted=o.halted,
        cur=[s.current_round for s in o.stores],
        hqc=[s.hqc_round for s in o.stores],
        htc=[s.htc_round for s in o.stores],
        hcr=[s.hcr for s in o.stores],
        cc=[c.commit_count for c in o.ctxs],
        lvr=[n.latest_voted_round for n in o.nxs],
        lock=[n.locked_round for n in o.nxs],
        tt=list(o.timer_time), ts=list(o.timer_stamp),
        qvalid=len(live),
        qtimes=sorted(m.time for m in live),
        qkinds=sorted(m.kind for m in live),
        qstamps=sorted(m.stamp for m in live),
        sent=o.n_msgs_sent, dropped=o.n_msgs_dropped, full=o.n_queue_full,
        pm_round=[pm.active_round for pm in o.pms],
    )


def main():
    seed = int(sys.argv[1]) if len(sys.argv) > 1 else 0
    max_ev = int(sys.argv[2]) if len(sys.argv) > 2 else 900
    p = SimParams(n_nodes=3, max_clock=1000)
    delay_table = jnp.asarray(p.delay_table())
    dur_table = jnp.asarray(p.duration_table())
    step = jax.jit(functools.partial(S.step, p, delay_table, dur_table))
    st = S.init_state(p, seed)
    orc = OracleSim(p, seed)
    a, b = snap_jax(st), snap_orc(orc)
    assert a == b, f"init mismatch: { {k: (a[k], b[k]) for k in a if a[k] != b[k]} }"
    for i in range(max_ev):
        st = step(st)
        orc.step()
        a, b = snap_jax(st), snap_orc(orc)
        if a != b:
            print(f"DIVERGED at event {i + 1}")
            for k in a:
                if a[k] != b[k]:
                    print(f"  {k}: jax={a[k]} oracle={b[k]}")
            return
        if a["halted"]:
            print(f"both halted at event {i + 1}, identical")
            return
    print(f"no divergence in {max_ev} events")


if __name__ == "__main__":
    main()
