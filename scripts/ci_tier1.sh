#!/usr/bin/env bash
# Tier-1 CI gate: the exact verify command from ROADMAP.md, plus the
# compile-time kernel-census regression check from PR 1.
#
# The census budget is the tpu_shape top-level fusion count recorded in
# KERNEL_CENSUS_r06.json (205 at n=4/B=2048, CPU-lowering proxy) plus
# ~7% headroom; a PR that pushes the serial step's kernel count back
# above it fails here without needing the TPU tunnel.  The telemetry-on
# graph (SimParams.telemetry) gets its own budget from the
# tpu_shape_telemetry count recorded in KERNEL_CENSUS_r07.json (214 =
# tpu_shape + 9 fusions for the metrics plane + flight recorder) plus the
# same headroom — telemetry OFF must stay inside the original budget
# (observability must cost zero kernels when disabled), telemetry ON must
# stay bounded.  The round-9 consensus watchdog gets the OFF budget as its
# ON budget (it measured zero top-level fusion cost — see
# KERNEL_CENSUS_r09.json and PERF_NOTES round 9).
#
# The 870 s pytest timeout is EXPECTED on this container (the suite is
# XLA-compile-bound: the PR-1 baseline is DOTS_PASSED=49 at the timeout
# with zero failures, vs 39 at the seed).  rc=124 therefore passes as
# long as no test actually failed/errored and the dot count holds the
# floor; any other nonzero rc, any F/E, or a dot regression fails.
#
# Usage: bash scripts/ci_tier1.sh
set -u
cd "$(dirname "$0")/.."

CENSUS_BUDGET=${CENSUS_BUDGET:-220}
TELEMETRY_CENSUS_BUDGET=${TELEMETRY_CENSUS_BUDGET:-230}
SHARDED_CENSUS_BUDGET=${SHARDED_CENSUS_BUDGET:-238}
# The consensus watchdog (telemetry/stream.py) measured ZERO top-level
# fusion cost at the bench shape (tpu_shape_watchdog == tpu_shape == 205,
# KERNEL_CENSUS_r09.json — the detectors fuse into existing kernels), so
# its budget equals the off budget: a regression that makes the watchdog
# cost kernels fails here even if the off graph stays clean.
WATCHDOG_CENSUS_BUDGET=${WATCHDOG_CENSUS_BUDGET:-220}
TIER1_MIN_DOTS=${TIER1_MIN_DOTS:-39}

echo "=== collection check ==="
# Collection errors are invisible in the timeout pass-path below (pytest
# prints the ERRORS section only at end-of-run, which the 870 s timeout
# kills), so gate them separately: --collect-only is seconds and exits
# nonzero on any import/collection error.
if ! timeout -k 10 120 env JAX_PLATFORMS=cpu python -m pytest tests/ \
    --collect-only -q -m 'not slow' -p no:cacheprovider >/dev/null 2>&1; then
    echo "FAIL: test collection errors (run pytest --collect-only)" >&2
    exit 1
fi

echo "=== tier-1 test suite ==="
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
dots=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
fails=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd FE | wc -c)
echo "DOTS_PASSED=${dots} FAILS=${fails} rc=${rc}"

echo "=== 2-shard dp fleet parity + stream referees (explicit; the 870 s suite may time out before reaching them) ==="
# The fleet runtime's tier-1 referees: 2-shard parity for both engines at
# an odd batch, padding telemetry/oracle pinning, the one-[D]-digest-per-
# chunk halt-poll assertion, and the stream/watchdog oracle pins
# (tests/test_stream.py).  Runs from the persistent compile cache the
# suite pass above already populated.
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_multichip.py tests/test_stream.py -q -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly
parity_rc=$?

echo "=== kernel census regression gate (budgets: ${CENSUS_BUDGET} off / ${TELEMETRY_CENSUS_BUDGET} telemetry-on / ${WATCHDOG_CENSUS_BUDGET} watchdog-on / ${SHARDED_CENSUS_BUDGET} per-shard) ==="
JAX_PLATFORMS=cpu python scripts/kernel_census.py \
    --assert-max "${CENSUS_BUDGET}" \
    --assert-telemetry-max "${TELEMETRY_CENSUS_BUDGET}" \
    --assert-watchdog-max "${WATCHDOG_CENSUS_BUDGET}" \
    --assert-sharded-max "${SHARDED_CENSUS_BUDGET}"
census_rc=$?

tests_ok=0
if [ "$fails" -ne 0 ]; then
    echo "FAIL: ${fails} test failure(s)/error(s)" >&2
    tests_ok=1
elif [ "$dots" -lt "$TIER1_MIN_DOTS" ]; then
    echo "FAIL: DOTS_PASSED=${dots} below floor ${TIER1_MIN_DOTS}" >&2
    tests_ok=1
elif [ "$rc" -ne 0 ] && [ "$rc" -ne 124 ]; then
    echo "FAIL: tier-1 tests rc=$rc (not the expected timeout)" >&2
    tests_ok=1
fi
if [ "$tests_ok" -ne 0 ]; then
    exit 1
fi
if [ "$parity_rc" -ne 0 ]; then
    echo "FAIL: 2-shard dp fleet parity rc=$parity_rc" >&2
    exit 1
fi
if [ "$census_rc" -ne 0 ]; then
    echo "FAIL: kernel census regression rc=$census_rc" >&2
    exit "$census_rc"
fi
echo "CI tier-1: OK"
