#!/usr/bin/env bash
# Tier-1 CI gate: static audit -> the exact verify command from ROADMAP.md
# -> explicit referee tests -> the compile-time kernel-census gates.
#
# Ordering rationale: the graph/source audit (scripts/graph_audit.py)
# TRACES both engines' graphs — no XLA compile — so it catches a
# miscompile-class scatter, a float leak, a smuggled callback, an
# unregistered knob, or a budget literal in ~2 minutes, before the suite
# spends its 870 s compile budget and long before the census compiles.
#
# All numeric budgets are single-sourced in scripts/budgets.py (the eval
# below materializes them; caller-exported overrides win).  Provenance of
# each value is documented there, and the source lint (audit rule S4)
# fails this file if a literal default ever reappears here.
#
# The 870 s pytest timeout is EXPECTED on this container (the suite is
# XLA-compile-bound: the PR-1 baseline is DOTS_PASSED=49 at the timeout
# with zero failures, vs 39 at the seed).  rc=124 therefore passes as
# long as no test actually failed/errored and the dot count holds the
# floor; any other nonzero rc, any F/E, or a dot regression fails.
#
# Usage: bash scripts/ci_tier1.sh
set -u
cd "$(dirname "$0")/.."

eval "$(python scripts/budgets.py --sh)"

echo "=== collection check ==="
# Collection errors are invisible in the timeout pass-path below (pytest
# prints the ERRORS section only at end-of-run, which the 870 s timeout
# kills), so gate them separately: --collect-only is seconds and exits
# nonzero on any import/collection error.
if ! timeout -k 10 120 env JAX_PLATFORMS=cpu python -m pytest tests/ \
    --collect-only -q -m 'not slow' -p no:cacheprovider >/dev/null 2>&1; then
    echo "FAIL: test collection errors (run pytest --collect-only)" >&2
    exit 1
fi

echo "=== static audit v2, fast families (jaxpr R1-R6, source S1-S4, donation D1-D3, concurrency C1-C3) ==="
# Fail fast: these passes are traced or AST work — no XLA compile —
# so they fit the 600 s cap even on a virgin container.  The
# compiled-HLO family runs as its own staged leg AFTER the AOT
# prebuild below (which populates the persistent compile cache with
# exactly the chunk executables the HLO pass compiles; cold it would
# blow this stage's budget).  The artifact is always written.
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu python scripts/graph_audit.py \
    --assert-clean --no-hlo --out GRAPH_AUDIT_r19.json; then
    echo "FAIL: static audit not clean (see GRAPH_AUDIT_r19.json)" >&2
    exit 1
fi

echo "=== AOT executable store prebuild (utils/aot.py; non-fatal) ==="
# Build/refresh the AOT store so the 870 s suite LOADS its heavy chunk
# executables (aot-hit = deserialize seconds, no trace/lower/XLA compile)
# instead of re-deriving them — the cold-59-vs-warm-98-dot gap is exactly
# these compiles.  Incremental: shapes already in the store are loaded,
# not rebuilt, so a shipped store makes this a fast verification pass.
# Non-fatal by design: if the prebuild fails the suite falls back to
# whatever the persistent compile cache already holds; a stale/corrupt
# STORE ENTRY falls back to a fresh jit compile (which repopulates the
# persistent cache for the next run — export compiles bypass it, see
# utils/aot._export).  AOT_PREBUILD=0 skips.
if [ "${AOT_PREBUILD:-1}" != "0" ]; then
    if ! timeout -k 10 3000 env JAX_PLATFORMS=cpu \
        python scripts/warm_cache.py; then
        echo "WARN: aot prebuild failed/timed out; the suite falls back" \
             "to the persistent compile cache" >&2
    fi
    # Self-warming loop: the PREVIOUS tier-1 run's streamed ledger names
    # every chunk executable the suite actually compiled — export exactly
    # those (first adoption pays the compiles once; afterwards the
    # children just load-verify and exit).
    if [ -f /tmp/_t1_ledger.ndjson ]; then
        if ! timeout -k 10 3000 env JAX_PLATFORMS=cpu \
            python scripts/warm_cache.py \
            --from-ledger /tmp/_t1_ledger.ndjson; then
            echo "WARN: ledger-driven aot warm failed/timed out" \
                 "(non-fatal)" >&2
        fi
    fi
    python -m librabft_simulator_tpu.utils.aot --list || true
fi

echo "=== perf-regression sentinel (scripts/perf_sentinel.py: canonical rung matrix -> BENCH_HISTORY.ndjson; tolerance ${BENCH_SENTINEL_TOL_PCT}%) ==="
# Staged right after the AOT prebuild so the aot_ttfc rung measures the
# store-backed time-to-first-chunk (the headline the store exists for)
# and the other rungs load warm executables instead of timing XLA.
# Self-arming gate: with fewer than 3 prior history rows the sentinel
# records a baseline row and exits 0 (seeding runs can't fail CI); once
# history is deep enough a rung worse than its rolling median by more
# than the budgeted tolerance exits 2 — a hard FAIL below.  A timeout
# (rc 124) is a measurement failure, not a perf verdict: also fatal,
# since a sentinel that cannot finish its micro matrix means the matrix
# itself regressed catastrophically.
timeout -k 10 1500 env JAX_PLATFORMS=cpu \
    BENCH_SENTINEL_TOL_PCT="${BENCH_SENTINEL_TOL_PCT}" \
    python scripts/perf_sentinel.py
sentinel_rc=$?

echo "=== static audit v2, compiled-HLO leg (scatter class + provenance, digest-only root, alias survival) ==="
# The one audit family that invokes XLA, staged here so its three
# fleet-shape chunk compiles ride the persistent cache the prebuild
# just populated (seconds warm; the first-ever container run pays them
# once).  --engines "" --no-sharded skips the jaxpr matrix the fast
# stage already passed; the HLO artifact lands beside the main one.
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu python scripts/graph_audit.py \
    --assert-clean --engines "" --no-sharded --no-source --no-donation \
    --no-concurrency --out GRAPH_AUDIT_r19_hlo.json; then
    echo "FAIL: compiled-HLO audit not clean (see GRAPH_AUDIT_r19_hlo.json)" >&2
    exit 1
fi

echo "=== tier-1 test suite ==="
set -o pipefail
rm -f /tmp/_t1.log /tmp/_t1_ledger.ndjson
# LIBRABFT_LEDGER_OUT streams the runtime ledger (telemetry/ledger.py):
# every XLA compile the suite pays, keyed + cache-hit/miss-attributed, is
# flushed per row — so even the EXPECTED 870 s timeout kill leaves the
# full compile story on disk and the attribution step below can say
# where the budget went (the cold-vs-warm dot gap, explained by data).
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    LIBRABFT_LEDGER_OUT=/tmp/_t1_ledger.ndjson python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
dots=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
fails=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd FE | wc -c)
echo "DOTS_PASSED=${dots} FAILS=${fails} rc=${rc}"

echo "=== compile-vs-run wall-time attribution (runtime ledger) ==="
# Non-fatal: the summary is diagnosis, not a gate.  The JSON lands next
# to /tmp/_t1.log; the one-line headline prints the compile share.
if python -m librabft_simulator_tpu.telemetry.ledger \
    --attribution /tmp/_t1_ledger.ndjson \
    --out /tmp/_t1_compile_attribution.json > /dev/null 2>&1; then
    python - <<'EOF'
import json
with open("/tmp/_t1_compile_attribution.json") as f:
    a = json.load(f)
cvr = a["compile_vs_run"]
pc = a["compile"]["persistent_cache"]
aot = a["compile"].get("aot", {})
print(f"tier-1 attribution: compile {cvr['compile_s']}s vs run "
      f"{cvr['run_s']}s (compile fraction {cvr['compile_fraction']}); "
      f"{a['compile']['entries']} builds over "
      f"{a['compile']['distinct_keys']} structural keys, persistent cache "
      f"{pc['hits']} hits / {pc['misses']} misses, aot store "
      f"{aot.get('hits', 0)} hits / {aot.get('stale', 0)} stale "
      f"({aot.get('load_s', 0)}s load) "
      f"-> /tmp/_t1_compile_attribution.json")
EOF
else
    echo "runtime-ledger attribution unavailable (no ledger rows)" >&2
fi

echo "=== 2-shard dp fleet parity + stream + audit referees (explicit; the 870 s suite may time out before reaching them) ==="
# The fleet runtime's tier-1 referees: 2-shard parity for both engines at
# an odd batch, padding telemetry/oracle pinning, the one-[D]-digest-per-
# chunk halt-poll assertion, the stream/watchdog oracle pins
# (tests/test_stream.py), and the auditor's own referees — seeded-
# violation fixtures + engines-pass-clean + the checkify sanitizer smoke
# (tests/test_audit.py).  Runs from the persistent compile cache the
# suite pass above already populated.
timeout -k 10 900 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_multichip.py tests/test_stream.py tests/test_audit.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly
parity_rc=$?

echo "=== resident fleet service referees (tests/test_serve.py in FULL: heterogeneous-fleet parity, admission bit-identity, the one-digest-per-chunk resident poll pin) ==="
timeout -k 10 900 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_serve.py -q -p no:cacheprovider -p no:xdist -p no:randomly
serve_rc=$?

echo "=== adversary engine referees (tests/test_adversary.py in FULL: off/inert identity, static-mask window reproduction serial+lane+sharded, oracle parity under composed attacks, per-link lane horizon, attacks-as-requests one-compile pin) ==="
timeout -k 10 1200 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_adversary.py -q -p no:cacheprovider -p no:xdist -p no:randomly
adv_rc=$?

echo "=== AOT store referees (tests/test_aot.py in FULL — the store-backed round trips are slow-marked out of the 870 s suite because their export fixture deliberately pays ~4 fresh compiles) ==="
timeout -k 10 900 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_aot.py -q -p no:cacheprovider -p no:xdist -p no:randomly
aot_rc=$?

echo "=== multi-process local-cluster smoke (tests/test_distributed.py non-slow: 2-process parity + per-host egress + resize-under-fire; children warm /tmp/librabft_aot_dist — the first-ever run pays the export compiles, later runs aot-hit) ==="
# Hard timeout: a wedged gloo collective (dead peer) must never hang CI —
# the cluster harness reaps its children, and this cap reaps the harness.
# The distributed runtime adds ZERO traced ops to the chunk program (the
# graph_audit --assert-clean gate above re-verifies the sharded flavor's
# R5 digest-only contract unchanged with distributed/ in the tree).
timeout -k 10 1500 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_distributed.py -q -m 'not slow' -p no:cacheprovider \
    -p no:xdist -p no:randomly
dist_rc=$?

echo "=== fleet observatory referees (tests/test_observatory.py non-slow: cross-stream ingest/rollup pins, clock-offset trace merge, sentinel gate self-test) ==="
timeout -k 10 1200 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_observatory.py -q -m 'not slow' -p no:cacheprovider \
    -p no:xdist -p no:randomly
obs_rc=$?

echo "=== kernel census regression gate (budgets: ${CENSUS_BUDGET} off / ${TELEMETRY_CENSUS_BUDGET} telemetry-on / ${WATCHDOG_CENSUS_BUDGET} watchdog-on / ${SHARDED_CENSUS_BUDGET} per-shard / ${RING_K4_CENSUS_BUDGET} ring-k4 / ${RING_K16_CENSUS_BUDGET} ring-k16 / ${K4_CENSUS_BUDGET} k4 / ${K16_CENSUS_BUDGET} k16 macro / ${SCENARIO_CENSUS_BUDGET} scenario / ${ADVERSARY_CENSUS_BUDGET} adversary / ${ADVERSARY_LANE_CENSUS_BUDGET} adversary-lane) ==="
JAX_PLATFORMS=cpu python scripts/kernel_census.py \
    --assert-max "${CENSUS_BUDGET}" \
    --assert-telemetry-max "${TELEMETRY_CENSUS_BUDGET}" \
    --assert-watchdog-max "${WATCHDOG_CENSUS_BUDGET}" \
    --assert-sharded-max "${SHARDED_CENSUS_BUDGET}" \
    --assert-ring-k4-max "${RING_K4_CENSUS_BUDGET}" \
    --assert-ring-k16-max "${RING_K16_CENSUS_BUDGET}" \
    --assert-k4-max "${K4_CENSUS_BUDGET}" \
    --assert-k16-max "${K16_CENSUS_BUDGET}" \
    --assert-scenario-max "${SCENARIO_CENSUS_BUDGET}" \
    --assert-adversary-max "${ADVERSARY_CENSUS_BUDGET}" \
    --assert-adversary-lane-max "${ADVERSARY_LANE_CENSUS_BUDGET}"
census_rc=$?

tests_ok=0
if [ "$fails" -ne 0 ]; then
    echo "FAIL: ${fails} test failure(s)/error(s)" >&2
    tests_ok=1
elif [ "$dots" -lt "$TIER1_MIN_DOTS" ]; then
    echo "FAIL: DOTS_PASSED=${dots} below floor ${TIER1_MIN_DOTS}" >&2
    tests_ok=1
elif [ "$rc" -ne 0 ] && [ "$rc" -ne 124 ]; then
    echo "FAIL: tier-1 tests rc=$rc (not the expected timeout)" >&2
    tests_ok=1
fi
if [ "$tests_ok" -ne 0 ]; then
    exit 1
fi
if [ "$parity_rc" -ne 0 ]; then
    echo "FAIL: fleet parity / stream / audit referees rc=$parity_rc" >&2
    exit 1
fi
if [ "$serve_rc" -ne 0 ]; then
    echo "FAIL: resident fleet service referees rc=$serve_rc" >&2
    exit 1
fi
if [ "$adv_rc" -ne 0 ]; then
    echo "FAIL: adversary engine referees rc=$adv_rc" >&2
    exit 1
fi
if [ "$aot_rc" -ne 0 ]; then
    echo "FAIL: AOT store referees rc=$aot_rc" >&2
    exit 1
fi
if [ "$dist_rc" -ne 0 ]; then
    echo "FAIL: multi-process local-cluster referees rc=$dist_rc" >&2
    exit 1
fi
if [ "$obs_rc" -ne 0 ]; then
    echo "FAIL: fleet observatory referees rc=$obs_rc" >&2
    exit 1
fi
if [ "$sentinel_rc" -ne 0 ]; then
    echo "FAIL: perf sentinel rc=$sentinel_rc (2 = rung regression vs" \
         "BENCH_HISTORY.ndjson baseline; anything else = the micro" \
         "matrix could not be measured)" >&2
    exit 1
fi
if [ "$census_rc" -ne 0 ]; then
    echo "FAIL: kernel census regression rc=$census_rc" >&2
    exit "$census_rc"
fi
echo "CI tier-1: OK"
