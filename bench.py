"""Round-contract benchmark: aggregate consensus-round throughput.

Runs a fleet of independent LibraBFTv2 instances (BASELINE config #2 shape:
4 nodes per instance) as one jitted, vmapped step function and reports

    {"metric": "rounds_per_sec", "value": ..., "unit": "rounds/sec",
     "vs_baseline": value / 1e6, ...}

on a single line of stdout.  ``vs_baseline`` is against the reference north
star of >=1M consensus rounds/sec aggregate (BASELINE.json).

Environment knobs: BENCH_B (instances), BENCH_STEPS (timed events/instance),
BENCH_NODES, BENCH_SWEEP=1 to also print per-config lines for BASELINE
configs 1-5 (stderr, not the contract line).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

import jax

os.makedirs("/tmp/librabft_tpu_jax_cache", exist_ok=True)
jax.config.update("jax_compilation_cache_dir", "/tmp/librabft_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import jax.numpy as jnp

from librabft_simulator_tpu.core.types import SimParams
from librabft_simulator_tpu.sim import simulator as S


def fleet_rounds(st) -> int:
    """Rounds completed per instance = max round any of its nodes reached
    (current_round starts at 1); summed over the fleet."""
    cur = jax.device_get(st.store.current_round)  # [B, N]
    return int(np.sum(np.max(cur, axis=-1) - 1))


def fleet_commits(st) -> int:
    return int(np.sum(jax.device_get(st.ctx.commit_count)))


def run_bench(n_nodes: int, batch: int, chunk: int = 128, reps: int = 4,
              delay_kind: str = "uniform", drop: float = 0.0):
    """One compiled ``chunk``-step scan, reused: 1 warmup call + ``reps``
    timed calls (a single XLA program keeps compile time bounded)."""
    p = SimParams(
        n_nodes=n_nodes,
        delay_kind=delay_kind,
        drop_prob=drop,
        max_clock=2**30,  # never halt inside the timed window
        queue_cap=max(32, 4 * n_nodes),
    )
    seeds = np.arange(batch, dtype=np.uint32)
    st = S.init_batch(p, seeds)
    st = S.dedupe_buffers(st)
    run = S.make_run_fn(p, chunk)
    st = run(st)  # compile + reach steady state
    jax.block_until_ready(st)
    r0, c0 = fleet_rounds(st), fleet_commits(st)
    t0 = time.perf_counter()
    for _ in range(reps):
        st = run(st)
    jax.block_until_ready(st)
    dt = time.perf_counter() - t0
    r1, c1 = fleet_rounds(st), fleet_commits(st)
    return {
        "rounds_per_sec": (r1 - r0) / dt,
        "commits_per_sec": (c1 - c0) / dt,
        "events_per_sec": batch * chunk * reps / dt,
        "elapsed_s": dt,
        "instances": batch,
        "n_nodes": n_nodes,
        "steps": chunk * reps,
    }


def main():
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    batch = int(os.environ.get("BENCH_B", 32768 if on_tpu else 2048))
    chunk = int(os.environ.get("BENCH_STEPS", 128 if on_tpu else 64))
    reps = int(os.environ.get("BENCH_REPS", 4 if on_tpu else 2))
    n_nodes = int(os.environ.get("BENCH_NODES", 4))
    res = run_bench(n_nodes, batch, chunk, reps)
    out = {
        "metric": "rounds_per_sec",
        "value": round(res["rounds_per_sec"], 1),
        "unit": "rounds/sec",
        "vs_baseline": round(res["rounds_per_sec"] / 1e6, 4),
        "commits_per_sec": round(res["commits_per_sec"], 1),
        "events_per_sec": round(res["events_per_sec"], 1),
        "instances": res["instances"],
        "n_nodes": n_nodes,
        "platform": platform,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
