"""Round-contract benchmark: aggregate consensus-round throughput.

Runs a fleet of independent LibraBFTv2 instances (BASELINE config #2 shape:
4 nodes per instance) and reports

    {"metric": "rounds_per_sec", "value": ..., "unit": "rounds/sec",
     "vs_baseline": value / 1e6, ...}

on a single line of stdout.  ``vs_baseline`` is against the reference north
star of >=1M consensus rounds/sec aggregate (BASELINE.json).

Platform handling (the part that decides whether this file produces a number
at all): the environment's TPU plugin can HANG backend init indefinitely when
the TPU tunnel is down and it ignores ``JAX_PLATFORMS``.  So before touching
any backend in-process we probe the default backend in a *subprocess with a
timeout*; on failure/timeout we force the CPU backend via
``jax.config.update("jax_platforms", "cpu")`` (which the plugin does honor)
and still print the contract line with a truthful ``platform`` field.  Any
in-run failure re-execs once with ``BENCH_PLATFORM=cpu``; the last-resort
path prints a contract line with ``value: 0`` and an ``error`` field.

Environment knobs: BENCH_PLATFORM (cpu|default: skip the probe),
BENCH_PROBE_TIMEOUT, BENCH_B (instances), BENCH_STEPS (events or windows per
rep), BENCH_REPS, BENCH_NODES, BENCH_ENGINE (parallel|serial|both).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time


def _decide_platform() -> str:
    forced = os.environ.get("BENCH_PLATFORM")
    if forced:
        return forced
    timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "75"))
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print('PLATFORM=' + jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout)
        for line in (r.stdout or "").splitlines():
            if line.startswith("PLATFORM="):
                return line[len("PLATFORM="):].strip() or "cpu"
    except Exception:
        pass
    return "cpu"


_PLATFORM = _decide_platform()

import jax  # noqa: E402

if _PLATFORM == "cpu":
    # Must land before any backend init; the config flag beats plugins that
    # ignore the JAX_PLATFORMS env var.
    jax.config.update("jax_platforms", "cpu")

os.makedirs("/tmp/librabft_tpu_jax_cache", exist_ok=True)
jax.config.update("jax_compilation_cache_dir", "/tmp/librabft_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import numpy as np  # noqa: E402


def _fleet_rounds(current_round) -> int:
    """Rounds completed per instance = max round any of its nodes reached
    (current_round starts at 1); summed over the fleet."""
    cur = jax.device_get(current_round)  # [B, N]
    return int(np.sum(np.max(cur, axis=-1) - 1))


def _time_engine(engine, p, batch, chunk, reps):
    """1 warmup call of one compiled chunk-scan + ``reps`` timed calls."""
    seeds = np.arange(batch, dtype=np.uint32)
    st = engine.init_batch(p, seeds)
    from librabft_simulator_tpu.sim.simulator import dedupe_buffers

    st = dedupe_buffers(st)
    run = engine.make_run_fn(p, chunk)
    t_c = time.perf_counter()
    st = run(st)  # compile + reach steady state
    jax.block_until_ready(st)
    compile_s = time.perf_counter() - t_c
    r0 = _fleet_rounds(st.store.current_round)
    c0 = int(np.sum(jax.device_get(st.ctx.commit_count)))
    e0 = int(np.sum(jax.device_get(st.n_events)))
    t0 = time.perf_counter()
    for _ in range(reps):
        st = run(st)
    jax.block_until_ready(st)
    dt = time.perf_counter() - t0
    r1 = _fleet_rounds(st.store.current_round)
    c1 = int(np.sum(jax.device_get(st.ctx.commit_count)))
    e1 = int(np.sum(jax.device_get(st.n_events)))
    return {
        "rounds_per_sec": (r1 - r0) / dt,
        "commits_per_sec": (c1 - c0) / dt,
        "events_per_sec": (e1 - e0) / dt,
        "elapsed_s": dt,
        "compile_s": compile_s,
    }


def run_bench(n_nodes: int, batch: int, chunk: int, reps: int,
              engine_name: str, delay_kind: str = "uniform",
              drop: float = 0.0) -> dict:
    from librabft_simulator_tpu.core.types import SimParams
    from librabft_simulator_tpu.sim import parallel_sim, simulator

    engine = parallel_sim if engine_name == "parallel" else simulator
    p = SimParams(
        n_nodes=n_nodes,
        delay_kind=delay_kind,
        drop_prob=drop,
        max_clock=2**30,  # never halt inside the timed window
        queue_cap=max(32, 4 * n_nodes),
    )
    res = _time_engine(engine, p, batch, chunk, reps)
    res.update(instances=batch, n_nodes=n_nodes, steps=chunk * reps,
               engine=engine_name)
    return res


def run_all() -> dict:
    platform = jax.devices()[0].platform
    on_tpu = platform != "cpu"
    batch = int(os.environ.get("BENCH_B", 32768 if on_tpu else 2048))
    chunk = int(os.environ.get("BENCH_STEPS", 128 if on_tpu else 32))
    reps = int(os.environ.get("BENCH_REPS", 4 if on_tpu else 2))
    n_nodes = int(os.environ.get("BENCH_NODES", 4))
    mode = os.environ.get("BENCH_ENGINE", "both")

    results = {}
    if mode in ("parallel", "both"):
        results["parallel"] = run_bench(n_nodes, batch, chunk, reps, "parallel")
    if mode in ("serial", "both"):
        results["serial"] = run_bench(
            n_nodes, batch, chunk, reps, "serial")
    head = results.get("parallel") or results["serial"]
    out = {
        "metric": "rounds_per_sec",
        "value": round(head["rounds_per_sec"], 1),
        "unit": "rounds/sec",
        "vs_baseline": round(head["rounds_per_sec"] / 1e6, 4),
        "engine": head["engine"],
        "commits_per_sec": round(head["commits_per_sec"], 1),
        "events_per_sec": round(head["events_per_sec"], 1),
        "compile_s": round(head["compile_s"], 1),
        "instances": head["instances"],
        "n_nodes": head["n_nodes"],
        "platform": platform,
    }
    if "serial" in results and "parallel" in results:
        out["serial_rounds_per_sec"] = round(
            results["serial"]["rounds_per_sec"], 1)
    return out


def main():
    try:
        out = run_all()
    except Exception as e:  # noqa: BLE001 - contract line must still print
        if _PLATFORM != "cpu":
            # Retry once on the always-available backend.
            env = dict(os.environ, BENCH_PLATFORM="cpu")
            r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                               env=env)
            sys.exit(r.returncode)
        out = {
            "metric": "rounds_per_sec", "value": 0.0, "unit": "rounds/sec",
            "vs_baseline": 0.0, "platform": "none",
            "error": f"{type(e).__name__}: {e}"[:300],
        }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
