"""Round-contract benchmark: aggregate consensus-round throughput.

Runs a fleet of independent LibraBFTv2 instances (BASELINE config #2 shape:
4 nodes per instance) and reports

    {"metric": "rounds_per_sec", "value": ..., "unit": "rounds/sec",
     "vs_baseline": value / 1e6, ...}

on a single line of stdout.  ``vs_baseline`` is against the reference north
star of >=1M consensus rounds/sec aggregate (BASELINE.json).

Platform handling (the part that decides whether this file produces a number
at all): the environment's TPU plugin tunnels to a remote chip; backend init
can take *minutes* (the remote end recycles one client session at a time) and
hangs indefinitely when the tunnel is down.  Probing in killed subprocesses
makes this WORSE — every killed prober holds the remote session and wedges
the tunnel for the next attempt (observed: three 120 s probe timeouts in a
row while the chip was healthy).  So: a SUPERVISOR process (the default
``python bench.py`` entry) spawns the real bench as a child, which attaches
exactly once and touches a marker file the moment ``jax.devices()`` returns;
if the marker hasn't appeared within ``BENCH_INIT_TIMEOUT`` seconds the
supervisor kills the child and reruns it with ``BENCH_PLATFORM=cpu`` (a hung
PJRT init may hold the GIL, so the guard cannot be an in-process thread).
A dead tunnel (relay not listening) is detected in milliseconds instead.
The attach outcome rides along in ``BENCH_PROBE_DIAG`` so the emitted JSON
is self-explaining; any in-run failure re-execs once with
``BENCH_PLATFORM=cpu``; the last-resort path prints a contract line with
``value: 0`` and an ``error`` field.

Environment knobs: BENCH_PLATFORM (cpu|default: skip the attach watchdog),
BENCH_INIT_TIMEOUT (s, default 600), BENCH_B (instances), BENCH_STEPS
(events or windows per rep), BENCH_REPS, BENCH_NODES, BENCH_ENGINE
(parallel|serial|both).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from librabft_simulator_tpu.utils.rlimit import raise_stack_limit  # noqa: E402

raise_stack_limit()


def _cpu_reexec(diag: dict):
    """Replace this process with a CPU-pinned rerun, carrying diagnostics."""
    env = dict(os.environ, BENCH_PLATFORM="cpu",
               BENCH_PROBE_DIAG=json.dumps(diag))
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)


def _tunnel_listening() -> bool:
    """The TPU plugin reaches its pool service through a local relay
    (AXON_POOL_SVC_OVERRIDE=127.0.0.1).  If nothing listens there the plugin
    spins in a connect-retry loop forever — detect that in milliseconds
    instead of burning the attach watchdog."""
    import socket

    # Known relay ports of the loopback tunnel; overridable if the relay
    # moves (a wrong list would demote a healthy TPU run to CPU).
    try:
        ports = tuple(
            int(x) for x in
            os.environ.get("BENCH_TUNNEL_PORTS", "8082,8083,8087").split(",")
            if x.strip()) or (8082, 8083, 8087)
    except ValueError:
        print("bench: ignoring malformed BENCH_TUNNEL_PORTS", file=sys.stderr)
        ports = (8082, 8083, 8087)
    for port in ports:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=5.0):
                return True
        except OSError:
            continue
    return False


def _supervise() -> "None":
    """Run the real bench as a child process and guard its backend attach.

    A hung PJRT init may hold the GIL, so an in-process watchdog thread is
    not guaranteed to ever run — the guard must live in a separate process.
    The child touches the attach-marker file right after ``jax.devices()``
    returns; until then a BENCH_INIT_TIMEOUT clock runs.  On timeout the
    child is killed and a BENCH_PLATFORM=cpu child produces the contract
    line instead (stdout is inherited either way)."""
    import tempfile

    fd, marker = tempfile.mkstemp(prefix="bench_attach_")
    os.close(fd)
    os.unlink(marker)
    env = dict(os.environ, BENCH_SUPERVISED="1", BENCH_ATTACH_MARKER=marker)
    child = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                             env=env)
    timeout = float(os.environ.get("BENCH_INIT_TIMEOUT", "600"))
    t0 = time.time()
    attached = False
    while True:
        rc = child.poll()
        if rc is not None:
            sys.exit(rc)
        attached = attached or os.path.exists(marker)
        if not attached and time.time() - t0 > timeout:
            child.kill()
            child.wait()
            diag = {"mode": "supervised", "forced": None,
                    "timeout_s": timeout,
                    "error": f"backend attach exceeded {timeout:.0f}s; "
                             "child killed, cpu fallback"}
            env2 = dict(os.environ, BENCH_PLATFORM="cpu",
                        BENCH_PROBE_DIAG=json.dumps(diag))
            r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                               env=env2)
            sys.exit(r.returncode)
        time.sleep(0.5)


if os.environ.get("BENCH_FLEET") or os.environ.get("BENCH_FLEET_CHILD"):
    # The dp fleet ladder is CPU-emulated by definition (virtual host
    # devices; the TPU tunnel rung lives on the ROADMAP revival checklist),
    # so neither the supervisor watchdog nor a TPU attach applies.
    os.environ.setdefault("BENCH_PLATFORM", "cpu")

if os.environ.get("BENCH_MACRO") or os.environ.get("BENCH_MACRO_CHILD"):
    # The macro K-ladder is a CPU-lowering proxy by definition (its
    # fusions-per-event census lowers on host; the on-chip ev/s rung is a
    # ROADMAP tunnel-checklist item), so no TPU attach applies here
    # either.
    os.environ.setdefault("BENCH_PLATFORM", "cpu")

if os.environ.get("BENCH_POD"):
    # The multi-process pod ladder is CPU-emulated by definition (local
    # jax.distributed processes over loopback; the real-slice rung lives
    # on the ROADMAP tunnel checklist).
    os.environ.setdefault("BENCH_PLATFORM", "cpu")

if os.environ.get("BENCH_RING") or os.environ.get("BENCH_RING_CHILD"):
    # The ring-dispatch ladder is a CPU-lowering proxy by definition
    # (virtual host devices measure poll amortization, not chip ev/s; the
    # on-chip ring re-measure is a ROADMAP tunnel-checklist item).
    os.environ.setdefault("BENCH_PLATFORM", "cpu")

if (__name__ == "__main__" and not os.environ.get("BENCH_SUPERVISED")
        and not os.environ.get("BENCH_PLATFORM")):
    _supervise()  # never returns


def _touch_attach_marker() -> None:
    """Tell the supervisor the attach phase is over (on EVERY resolution
    path — a CPU fallback that skips the marker would be killed mid-run at
    BENCH_INIT_TIMEOUT and rerun from scratch)."""
    marker = os.environ.get("BENCH_ATTACH_MARKER")
    if marker:
        open(marker, "w").close()


def _attach_backend() -> tuple[str, dict]:
    """One in-process backend attach (the supervisor process guards against
    a hang).  Returns (platform, diagnostics)."""
    diag = {"mode": "in-process", "forced": None, "init_seconds": None,
            "error": None}
    prior = os.environ.get("BENCH_PROBE_DIAG")
    if prior:
        try:
            diag = json.loads(prior)
        except ValueError:
            pass
    forced = os.environ.get("BENCH_PLATFORM")
    if forced:
        diag["forced"] = forced
        _touch_attach_marker()
        return forced, diag
    if os.environ.get("PALLAS_AXON_POOL_IPS") and not _tunnel_listening():
        diag["error"] = "tpu tunnel relay not listening (dead tunnel)"
        jax.config.update("jax_platforms", "cpu")
        _touch_attach_marker()
        return "cpu", diag
    t0 = time.perf_counter()
    try:
        platform = jax.devices()[0].platform
    except Exception as e:  # noqa: BLE001 - plugin init failure
        diag["error"] = f"{type(e).__name__}: {e}"[:300]
        diag["init_seconds"] = round(time.perf_counter() - t0, 1)
        _touch_attach_marker()
        _cpu_reexec(diag)
    diag["init_seconds"] = round(time.perf_counter() - t0, 1)
    _touch_attach_marker()
    return platform, diag


import jax  # noqa: E402

if os.environ.get("BENCH_PLATFORM") == "cpu":
    # Must land before any backend init; the config flag beats plugins that
    # ignore the JAX_PLATFORMS env var.
    jax.config.update("jax_platforms", "cpu")

_PLATFORM, _PROBE_DIAG = _attach_backend()

from librabft_simulator_tpu.utils.cache import setup_compile_cache  # noqa: E402

setup_compile_cache()

import numpy as np  # noqa: E402


def _fleet_rounds(current_round) -> int:
    """Rounds completed per instance = max round any of its nodes reached
    (current_round starts at 1); summed over the fleet."""
    cur = jax.device_get(current_round)  # [B, N]
    return int(np.sum(np.max(cur, axis=-1) - 1))


def _time_engine(engine, p, batch, chunk, reps, init_kw=None):
    """1 warmup call of one compiled chunk-scan + ``reps`` timed calls.
    Both sections are runtime-ledger spans (telemetry/ledger.py), so the
    compile attribution and the timed window land in the same host-side
    record the fleet runtime uses."""
    import jax.numpy as jnp
    from librabft_simulator_tpu.sim.simulator import dedupe_buffers
    from librabft_simulator_tpu.telemetry import ledger as tledger

    seeds = np.arange(batch, dtype=np.uint32)
    if init_kw:
        st = jax.vmap(lambda s: engine.init_state(p, s, **init_kw))(
            jnp.asarray(seeds))
    else:
        st = engine.init_batch(p, seeds)
    st = dedupe_buffers(st)
    run = engine.make_run_fn(p, chunk)
    lg = tledger.get()
    with lg.span(tledger.DISPATCH, what="bench_warmup") as sp_c:
        st = run(st)  # compile + reach steady state
        jax.block_until_ready(st)
    compile_s = sp_c.dur_s
    r0 = _fleet_rounds(st.store.current_round)
    c0 = int(np.sum(jax.device_get(st.ctx.commit_count)))
    e0 = int(np.sum(jax.device_get(st.n_events)))
    with lg.span(tledger.RUN, what="bench_timed", reps=reps) as sp_t:
        for _ in range(reps):
            st = run(st)
        jax.block_until_ready(st)
    dt = sp_t.dur_s
    r1 = _fleet_rounds(st.store.current_round)
    c1 = int(np.sum(jax.device_get(st.ctx.commit_count)))
    e1 = int(np.sum(jax.device_get(st.n_events)))
    # Fidelity: fraction of sends lost to queue/inbox overflow (0 = faithful).
    lost_field = st.n_queue_full if hasattr(st, "n_queue_full") else st.n_inbox_full
    lost = int(np.sum(jax.device_get(lost_field)))
    sent = int(np.sum(jax.device_get(st.n_msgs_sent)))
    max_epoch = int(np.max(jax.device_get(st.store.epoch_id)))
    if not p.epoch_handoff:
        # The handoff machinery is benched off on the premise that no epoch
        # boundary occurs inside the timed window (commit counts stay far
        # below commands_per_epoch).  Verify it: a workload change that
        # crosses a boundary would otherwise silently bench a config that
        # can deadlock at boundaries (test_epoch_handoff.py).
        assert max_epoch == 0, (
            f"bench crossed an epoch boundary (max epoch {max_epoch}) with "
            "epoch_handoff=False; re-bench with the default handoff config")
    res = {
        "max_epoch": max_epoch,
        "rounds_per_sec": (r1 - r0) / dt,
        "commits_per_sec": (c1 - c0) / dt,
        "events_per_sec": (e1 - e0) / dt,
        "elapsed_s": dt,
        "compile_s": compile_s,
        "overflow_frac": round(lost / max(sent + lost, 1), 4),
    }
    if not hasattr(st, "n_queue_full"):
        # Parallel engine: window occupancy = events processed per
        # instance-window (ceiling = lanes * drain per window).
        from librabft_simulator_tpu.sim.parallel_sim import drain_of, lanes_of

        res["window_occupancy"] = round(
            (e1 - e0) / max(chunk * reps * batch, 1), 2)
        res["occupancy_ceiling"] = lanes_of(p) * drain_of(p)
    if p.telemetry:
        # In-graph telemetry plane (telemetry/plane.py), decoded once after
        # the timed window: event-kind counts, loss tallies, queue pressure,
        # and p50/p99 latency bucket bounds, merged over the fleet.
        from librabft_simulator_tpu.telemetry import report as tel_report

        res["telemetry"] = tel_report.telemetry_block(p, st)
    return res


def run_bench(n_nodes: int, batch: int, chunk: int, reps: int,
              engine_name: str, delay_kind: str = "uniform",
              drop: float = 0.0, **params_kw) -> dict:
    from librabft_simulator_tpu.core.types import SimParams
    from librabft_simulator_tpu.sim import parallel_sim, simulator

    engine = parallel_sim if engine_name == "parallel" else simulator
    init_kw = params_kw.pop("init_kw", None)
    params_kw.setdefault("queue_cap", max(32, 4 * n_nodes))
    # The benched workloads commit a few hundred times per instance, far
    # below commands_per_epoch=30000, so no epoch boundary can occur inside
    # the timed window: with the handoff machinery off the trajectories are
    # bit-identical and the step graph is smaller (measured on CPU at
    # B=2048: 15% runtime + 5x compile-time tax when left on).  Recorded in
    # the output.
    params_kw.setdefault("epoch_handoff", False)
    # BENCH_SELECT=pallas A/Bs the fused event-select kernel on TPU.  The
    # compiled kernel cannot run on the CPU backend, so any CPU fallback
    # (dead tunnel, attach timeout, in-run failure rerun) downgrades to the
    # XLA select rather than poisoning the fallback contract line.
    # BENCH_TELEMETRY=1 runs the bench with the in-graph telemetry plane on
    # and attaches its decoded block to the contract line.  Off by default:
    # the headline number stays the cost of the bare step graph.
    from librabft_simulator_tpu.utils.xops import _bool_env

    params_kw.setdefault("telemetry", _bool_env("BENCH_TELEMETRY") or False)
    select = os.environ.get("BENCH_SELECT", "xla")
    if select == "pallas" and jax.devices()[0].platform == "cpu":
        select = "xla"
    if engine_name == "serial":  # the parallel engine has no select path
        params_kw.setdefault("select_kernel", select)
    # Unroll the protocol-interior scans on TPU: their while-loops are ~half
    # the on-chip step time (+18% events/s measured at B=2048), while on CPU
    # rolled scans are faster to compile and equally fast to run.  Gated to
    # n <= 16 because the timeout-batch scan body is replicated n times when
    # unrolled — wider fleets (n=32/64 sweep shapes) keep rolled scans to
    # protect the compile budget.
    params_kw.setdefault(
        "unroll", jax.devices()[0].platform != "cpu" and n_nodes <= 16)
    p = SimParams(
        n_nodes=n_nodes,
        delay_kind=delay_kind,
        drop_prob=drop,
        max_clock=2**30,  # never halt inside the timed window
        **params_kw,
    )
    res = _time_engine(engine, p, batch, chunk, reps, init_kw=init_kw)
    res.update(instances=batch, n_nodes=n_nodes, steps=chunk * reps,
               engine=engine_name, epoch_handoff=p.epoch_handoff,
               # Only the serial engine has a select_kernel code path.
               select_kernel=(p.select_kernel if engine_name == "serial"
                              else "n/a"))
    return res


def run_all() -> dict:
    platform = jax.devices()[0].platform
    on_tpu = platform != "cpu"
    # Same B/chunk on both backends so the TPU headline is directly
    # comparable to the CPU-fallback and prior-round numbers.  Measured on
    # chip (BENCH_TPU_LADDER_r05.json): events/s is FLAT in B from 2048 to
    # 32768 (the step is kernel-count-bound, not width-bound), so a bigger
    # fleet only drags rounds_per_sec down via the later, slower-round
    # regime; and calls of B*chunk >= ~4M events exceed the tunnel relay's
    # execution window and fault the device.
    batch = int(os.environ.get("BENCH_B", 2048))
    chunk = int(os.environ.get("BENCH_STEPS", 32))
    reps = int(os.environ.get("BENCH_REPS", 4 if on_tpu else 2))
    n_nodes = int(os.environ.get("BENCH_NODES", 4))
    mode = os.environ.get("BENCH_ENGINE", "both")

    results = {}
    failures = {}
    # Serial first: its executable is usually warm in the persistent cache,
    # so a flaky remote compile of the OTHER engine can't forfeit the whole
    # TPU measurement.  Each engine gets one retry (the tunnel's remote
    # compile service fails transiently: HTTP 500s, truncated bodies).
    order = [e for e in ("serial", "parallel") if mode in (e, "both")]
    for engine_name in order:
        for attempt in (1, 2):
            try:
                results[engine_name] = run_bench(
                    n_nodes, batch, chunk, reps, engine_name)
                break
            except Exception as e:  # noqa: BLE001 - isolate engine failures
                failures[engine_name] = f"{type(e).__name__}: {e}"[:200]
                print(f"bench: {engine_name} attempt {attempt} failed "
                      f"({type(e).__name__})", file=sys.stderr)
    if not results:
        raise RuntimeError(
            f"all engines failed on {platform}: {failures}")
    # Headline = the fastest engine at this config (both are zero-loss at the
    # 4-node shape; overflow_frac records fidelity either way).
    head = max(results.values(), key=lambda r: r["rounds_per_sec"])
    out = {
        "metric": "rounds_per_sec",
        "value": round(head["rounds_per_sec"], 1),
        "unit": "rounds/sec",
        "vs_baseline": round(head["rounds_per_sec"] / 1e6, 4),
        "engine": head["engine"],
        "commits_per_sec": round(head["commits_per_sec"], 1),
        "events_per_sec": round(head["events_per_sec"], 1),
        "compile_s": round(head["compile_s"], 1),
        "overflow_frac": head["overflow_frac"],
        "epoch_handoff": head["epoch_handoff"],
        "select_kernel": head["select_kernel"],
        "instances": head["instances"],
        "n_nodes": head["n_nodes"],
        "platform": platform,
        "probe": _PROBE_DIAG,
    }
    if "telemetry" in head:
        out["telemetry"] = head["telemetry"]
    for name, r in results.items():
        if r is not head:
            out[f"{name}_rounds_per_sec"] = round(r["rounds_per_sec"], 1)
    for name, err in failures.items():
        if name not in results:
            out[f"{name}_error"] = err
    return out


# BASELINE.json's five configs: (name, kwargs for run_bench).  Engine choice
# per shape: serial (one event per instance-step, shared queue) wins at small
# n; the parallel windowed engine is the only *faithful* option at n >= 16,
# where the serial queue needs O(n^2) capacity to stop overflowing
# (overflow_frac in the output records this).
def sweep_configs(scale: float = 1.0):
    from librabft_simulator_tpu.sim.byzantine import byz_masks
    from librabft_simulator_tpu.core.types import SimParams

    b = lambda x: max(int(x * scale), 1)  # noqa: E731
    eq4, _, _ = byz_masks(SimParams(n_nodes=4), 1, "equivocate")
    return [
        ("1_3node_single", dict(n_nodes=3, batch=1, engine_name="serial",
                                delay_kind="lognormal")),
        ("2_4node_10k_uniform", dict(n_nodes=4, batch=b(10000),
                                     engine_name="serial",
                                     delay_kind="uniform")),
        ("3_64node_1k_pareto_drop", dict(n_nodes=64, batch=b(1000),
                                         engine_name="parallel",
                                         delay_kind="pareto", drop=0.05)),
        ("4_byz_f1_10k", dict(n_nodes=4, batch=b(10000),
                              engine_name="serial", delay_kind="uniform",
                              init_kw=dict(byz_equivocate=eq4))),
        # inbox_cap ABOVE the 4n auto: uniform delays + fast 2-chain rounds
        # keep ~10n msgs in flight per node deep into the sim (measured:
        # auto 64 -> 43% overflow, 128 -> 19%, 256 -> 0.4%); the sweep
        # reports the faithful configuration.
        ("5_2chain_16node_10k", dict(n_nodes=16, batch=b(10000),
                                     engine_name="parallel",
                                     delay_kind="uniform", commit_chain=2,
                                     inbox_cap=256)),
    ]


def run_sweep(out_path: str) -> None:
    """Benchmark all five BASELINE configs; write one JSON object per config
    to ``out_path`` (stdout keeps the single-line contract)."""
    platform = jax.devices()[0].platform
    on_tpu = platform != "cpu"
    scale = float(os.environ.get("BENCH_SWEEP_SCALE", 1.0 if on_tpu else 0.1))
    chunk = int(os.environ.get("BENCH_STEPS", 64 if on_tpu else 16))
    reps = int(os.environ.get("BENCH_REPS", 2))
    try:
        only = int(os.environ.get("BENCH_SWEEP_ONLY", "0"))  # 1-based index
    except ValueError:
        print("bench: ignoring malformed BENCH_SWEEP_ONLY", file=sys.stderr)
        only = 0
    configs = sweep_configs(scale)
    if only and not 1 <= only <= len(configs):
        print(f"bench: BENCH_SWEEP_ONLY={only} out of range 1..{len(configs)};"
              " running all configs", file=sys.stderr)
        only = 0
    rows = []
    for idx, (name, kw) in enumerate(configs, start=1):
        if only and idx != only:
            continue
        try:
            r = run_bench(chunk=chunk, reps=reps, **kw)
            r["config"] = name
        except Exception as e:  # noqa: BLE001 - record and continue
            r = {"config": name, "error": f"{type(e).__name__}: {e}"[:300]}
        r["platform"] = platform
        rows.append(r)
        print(json.dumps(r), file=sys.stderr, flush=True)
    with open(out_path, "w") as f:
        json.dump({"platform": platform, "scale": scale, "configs": rows}, f,
                  indent=1)


# ---------------------------------------------------------------------------
# Fleet ladder (BENCH_FLEET=1): dp-mesh scaling sweep past the per-chip cap.
#
# The per-chip step is kernel-dispatch-bound (events/s flat in B,
# PERF_NOTES.md) and the remote-compile helper caps on-chip fleets at
# B=32768, so fleet throughput scales by adding DISPATCH ENGINES — the 'dp'
# mesh axis (parallel/sharded.py).  Each rung runs in its OWN SUBPROCESS
# with XLA_FLAGS=--xla_force_host_platform_device_count=<dp> (the proven
# tunnel-down MULTICHIP harness pattern): dp virtual CPU devices, a dp-shard
# mesh, B = BENCH_FLEET_B instances PER SHARD (weak scaling), the pipelined
# shard_map runner.  The artifact records aggregate events/s per rung and
# the scaling efficiency ev/s(dp) / (dp * ev/s(1)).  On this 2-core-class
# container the virtual devices timeshare the host, so CPU efficiency decays
# ~1/dp by construction — the artifact certifies the harness and the
# pipelined host loop; real scaling numbers come from rerunning on a
# multi-chip slice (ROADMAP tunnel checklist).
# ---------------------------------------------------------------------------


def _fleet_child() -> dict:
    """One ladder rung (this process owns its forced virtual-device count).

    The timed loop IS the production double-buffered shape
    (parallel/sharded.run_sharded): dispatch chunk k+1, then poll chunk
    k's LAGGED [D] digest — one small blocking fetch per chunk.  The
    runtime ledger (telemetry/ledger.py) records every dispatch-enqueue
    and poll as a span, so the rung lands a MEASURED pipeline-overlap
    fraction, dispatch-queue bubble flags, and the time_to_first_chunk
    headline (first dispatch start -> first digest on host, cold compile
    included) instead of the constructed-but-unmeasured claim."""
    import numpy as np
    from librabft_simulator_tpu.core.types import SimParams
    from librabft_simulator_tpu.parallel import mesh as mesh_ops
    from librabft_simulator_tpu.parallel import sharded
    from librabft_simulator_tpu.sim import parallel_sim, simulator
    from librabft_simulator_tpu.sim.simulator import dedupe_buffers
    from librabft_simulator_tpu.telemetry import ledger as tledger
    from librabft_simulator_tpu.telemetry import stream as tstream
    from librabft_simulator_tpu.utils.xops import _bool_env

    dp = int(os.environ["BENCH_FLEET_CHILD"])
    engine_name = os.environ.get("BENCH_FLEET_ENGINE", "serial")
    engine = parallel_sim if engine_name == "parallel" else simulator
    b_per = int(os.environ.get("BENCH_FLEET_B", 256))
    chunk = int(os.environ.get("BENCH_FLEET_STEPS", 16))
    reps = int(os.environ.get("BENCH_FLEET_REPS", 2))
    n_nodes = int(os.environ.get("BENCH_NODES", 4))
    streaming = _bool_env("BENCH_STREAM")
    batch = b_per * dp
    p = SimParams(n_nodes=n_nodes, delay_kind="uniform",
                  queue_cap=max(32, 4 * n_nodes), epoch_handoff=False,
                  max_clock=2**30,
                  watchdog=_bool_env("BENCH_WATCHDOG") or False)
    mesh = mesh_ops.make_mesh(n_dp=dp, n_mp=1, devices=jax.devices()[:dp])
    st = engine.init_batch(p, sharded.fleet_seeds(0, batch))
    st = mesh_ops.shard_batch(mesh, dedupe_buffers(st))
    run = sharded.make_sharded_run_fn(p, mesh, chunk, engine=engine)
    # BENCH_STREAM=1 additionally records the polled digests on a
    # TimelineRecorder (the NDJSON/FLEET_TIMELINE artifact); the poll
    # itself is always the production one-[D]-fetch-per-chunk contract.
    rec = tstream.TimelineRecorder(p, total_instances=batch) \
        if streaming else None
    lg = tledger.get()
    rid = lg.new_run("bench_fleet", devices=dp, instances=batch,
                     pipeline=True, chunk_steps=chunk)
    with lg.span(tledger.DISPATCH, run=rid, chunk=0) as sp_d0:
        st, dg = run(st)
    with lg.span(tledger.POLL, run=rid, chunk=0) as sp_p0:
        d0 = np.asarray(jax.device_get(dg))
    compile_s = sp_d0.dur_s + sp_p0.dur_s  # cold chunk 0: compile + run
    if rec is not None:
        rec.record(d0, steps=chunk)
    e0 = int(np.sum(jax.device_get(st.n_events)))
    r0 = _fleet_rounds(st.store.current_round)
    t0 = time.perf_counter()
    for i in range(reps):
        lagged = dg
        with lg.span(tledger.DISPATCH, run=rid, chunk=i + 1):
            st, dg = run(st)  # dispatch k+1 before polling chunk k
        if i >= 1:  # chunk 0's digest was already fetched for ttfc above
            with lg.span(tledger.POLL, run=rid, chunk=i):
                d = np.asarray(jax.device_get(lagged))
            if rec is not None:
                rec.record(d, steps=chunk * (i + 1))
    with lg.span(tledger.POLL, run=rid, chunk=reps):
        d_final = np.asarray(jax.device_get(dg))
    jax.block_until_ready(st)
    dt = time.perf_counter() - t0
    if rec is not None:
        rec.record(d_final, steps=chunk * (reps + 1))
    e1 = int(np.sum(jax.device_get(st.n_events)))
    r1 = _fleet_rounds(st.store.current_round)
    pipe = lg.pipeline_stats(run=rid)
    row = {
        "dp": dp, "engine": engine_name, "instances": batch,
        "per_shard_instances": b_per, "n_nodes": n_nodes,
        "steps": chunk * reps,
        "events_per_sec": round((e1 - e0) / dt, 1),
        "rounds_per_sec": round((r1 - r0) / dt, 1),
        "elapsed_s": round(dt, 3), "compile_s": round(compile_s, 1),
        "halted": int(d_final[tstream.SLOT["halted"]]),
        "watchdog": bool(p.watchdog),
        "ledger": {
            "time_to_first_chunk_s": pipe.get("time_to_first_chunk_s"),
            "overlap_fraction": pipe.get("overlap_fraction"),
            "bubble_count": pipe.get("bubble_count"),
            "chunk_rows": pipe.get("rows"),
            "compiles": [
                {k: e[k] for k in ("key", "engine", "shapes", "compile_s",
                                   "first_call_s", "cache")}
                for e in lg.compiles],
        },
    }
    if rec is not None:
        row["stream"] = rec.summary()
    return row


def _write_runtime_ledger(rows, fleet_artifact: str) -> None:
    """The RUNTIME_LEDGER artifact: every rung's measured host-side story
    — compile ledger (per structural key, persistent-cache hit/miss),
    per-chunk dispatch/poll spans, the double-buffered loop's measured
    overlap fraction and bubbles — with the time_to_first_chunk headline
    (the dp=1 rung's first-dispatch-to-first-digest wall time; the
    ROADMAP 'kill the compile tax' item is judged against this number)."""
    from librabft_simulator_tpu.telemetry import ledger as tledger

    led = [r for r in rows if r.get("ledger")]
    if not led:
        return
    head = next((r for r in led if r["dp"] == 1), led[0])
    path = os.environ.get("BENCH_LEDGER_OUT", "RUNTIME_LEDGER_r13.json")
    art = {
        "kind": "runtime_ledger",
        "ledger_version": tledger.LEDGER_VERSION,
        "platform": "cpu",
        "emulated": True,
        "fleet_artifact": fleet_artifact,
        "time_to_first_chunk_s": head["ledger"]["time_to_first_chunk_s"],
        "time_to_first_chunk_dp": head["dp"],
        "ttfc_aot_s": head["ledger"].get("ttfc_aot"),
        "ttfc_jit_s": head["ledger"].get("ttfc_jit"),
        "note": "time_to_first_chunk = first dispatch enqueue to the first "
                "chunk's [D] digest on host, XLA compile included "
                "(jax/backend import excluded); ttfc_aot/ttfc_jit = the "
                "same number from the per-rung cold-process A/B — the "
                "production path consulting the AOT executable store "
                "(utils/aot.py; compile verdicts say aot-hit when it "
                "loaded) vs LIBRABFT_AOT=0 (trace+lower+compile, "
                "persistent cache verdicts apply); overlap_fraction = "
                "poll_s/(poll_s+dispatch_s) over steady-state chunks of "
                "the double-buffered loop (~1.0 device-bound = dispatch "
                "fully hidden, ~0 host-bound); bubbles = chunks whose "
                "poll found the digest already on host (device idled). "
                "CPU rungs timeshare the host; re-measure on chip via "
                "the ROADMAP tunnel checklist.",
        "rungs": [{
            "dp": r["dp"], "engine": r["engine"],
            "instances": r["instances"], "steps": r["steps"],
            **r["ledger"],
        } for r in led],
    }
    with open(path, "w") as f:
        json.dump(art, f, indent=1)
    ab = (f"; aot={art['ttfc_aot_s']}s jit={art['ttfc_jit_s']}s"
          if art["ttfc_aot_s"] is not None else "")  # A/B may be skipped
    print(f"bench: wrote runtime-ledger artifact {path} "
          f"(time_to_first_chunk={art['time_to_first_chunk_s']}s at "
          f"dp={head['dp']}{ab})", file=sys.stderr)


def run_fleet_ladder(out_path: str) -> dict:
    """Drive one subprocess per dp rung; collect the MULTICHIP-style JSON."""
    try:
        rungs = [int(x) for x in
                 os.environ.get("BENCH_FLEET_DP", "1,2,4,8").split(",")
                 if x.strip()]
    except ValueError:
        print("bench: ignoring malformed BENCH_FLEET_DP", file=sys.stderr)
        rungs = [1, 2, 4, 8]
    base_flags = " ".join(
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    # AOT A/B (default on): each rung runs twice in cold processes — once
    # on the production path (AOT store consulted; ttfc_aot) and once
    # with LIBRABFT_AOT=0 (pure jit/persistent-cache path; ttfc_jit) —
    # so RUNTIME_LEDGER lands the measured compile-tax delta per rung
    # with the compile-ledger verdicts saying exactly what each leg paid
    # (aot-hit vs persistent-hit/miss).  BENCH_FLEET_AOT_AB=0 skips the
    # jit leg.
    from librabft_simulator_tpu.utils.xops import _bool_env

    aot_ab = _bool_env("BENCH_FLEET_AOT_AB") is not False

    def run_child(dp: int, aot_off: bool):
        env = dict(os.environ, BENCH_PLATFORM="cpu",
                   BENCH_FLEET_CHILD=str(dp),
                   XLA_FLAGS=(base_flags +
                              f" --xla_force_host_platform_device_count={dp}"
                              ).strip())
        env.pop("BENCH_FLEET", None)
        if aot_off:
            env["LIBRABFT_AOT"] = "0"
        r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                           env=env, capture_output=True, text=True)
        line = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
        try:
            return json.loads(line), None
        except ValueError:
            return None, f"rc={r.returncode}: {(r.stderr or line)[-300:]}"

    rows, failures = [], {}
    for dp in rungs:
        row, err = run_child(dp, aot_off=False)
        if row is None:
            failures[dp] = err
            print(f"bench: fleet rung dp={dp} failed ({err[:120]})",
                  file=sys.stderr)
            continue
        if aot_ab:
            ledger = row.setdefault("ledger", {})
            ledger["ttfc_aot"] = ledger.get("time_to_first_chunk_s")
            row_b, err_b = run_child(dp, aot_off=True)
            if row_b is None:
                print(f"bench: fleet rung dp={dp} jit A/B leg failed "
                      f"({(err_b or '')[:120]})", file=sys.stderr)
                ledger["ttfc_jit"] = None
            else:
                lb = row_b.get("ledger") or {}
                ledger["ttfc_jit"] = lb.get("time_to_first_chunk_s")
                ledger["ttfc_jit_compiles"] = lb.get("compiles")
        rows.append(row)
        print(json.dumps(row), file=sys.stderr, flush=True)
    base = next((r["events_per_sec"] for r in rows if r["dp"] == 1), None)
    if rows and not base:  # rung absent, or it measured 0 ev/s
        print("bench: no usable dp=1 baseline (rung missing or 0 events/s) "
              "— scaling_efficiency will be null on every rung",
              file=sys.stderr)
    for r in rows:
        r["scaling_efficiency"] = (
            round(r["events_per_sec"] / (r["dp"] * base), 3)
            if base else None)
    out = {
        "kind": "fleet_ladder",
        "platform": "cpu",
        "emulated": True,
        "host_cores": os.cpu_count(),
        "note": "weak scaling: B = per_shard_instances * dp; CPU rungs "
                "timeshare the host cores, so emulated efficiency decays "
                "~1/dp by construction — rerun on a real multi-chip slice "
                "(ROADMAP tunnel checklist) for the ICI curve",
        "rungs": rows,
        "failures": failures,
    }
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    _write_runtime_ledger(rows, out_path)
    if any("stream" in r for r in rows):
        # BENCH_STREAM=1: the per-rung digest timelines become their own
        # artifact — the fleet-health stream each rung polled per chunk
        # (telemetry/stream.py), with the slot registry version pinned.
        from librabft_simulator_tpu.telemetry import stream as tstream

        tl_path = os.environ.get("BENCH_STREAM_OUT",
                                 "FLEET_TIMELINE_r09.json")
        timeline = {
            "kind": "fleet_timeline",
            "registry_version": tstream.REGISTRY_VERSION,
            "digest_slots": [n for n, _ in tstream.DIGEST_SLOTS],
            "rungs": [{"dp": r["dp"], "engine": r["engine"],
                       "instances": r["instances"],
                       "stream": r["stream"]}
                      for r in rows if "stream" in r],
        }
        with open(tl_path, "w") as f:
            json.dump(timeline, f, indent=1)
        print(f"bench: wrote fleet timeline artifact {tl_path}",
              file=sys.stderr)
    head = {
        "metric": "fleet_events_per_sec",
        "value": rows[-1]["events_per_sec"] if rows else 0.0,
        "unit": "events/sec",
        "dp": rows[-1]["dp"] if rows else 0,
        "efficiency_curve": {str(r["dp"]): r["scaling_efficiency"]
                             for r in rows},
        "time_to_first_chunk_s": next(
            (r["ledger"]["time_to_first_chunk_s"] for r in rows
             if r.get("ledger") and r["dp"] == 1),
            next((r["ledger"]["time_to_first_chunk_s"] for r in rows
                  if r.get("ledger")), None)),
        "overlap_curve": {str(r["dp"]): r["ledger"]["overlap_fraction"]
                          for r in rows if r.get("ledger")},
        "artifact": out_path,
    }
    print(json.dumps(head))
    return out


# ---------------------------------------------------------------------------
# Macro-step K-ladder (BENCH_MACRO=1): events-per-dispatch scaling sweep.
#
# The serial step is kernel-dispatch-bound on chip (events/s flat in B,
# PERF_NOTES round 5); PR 1 cut kernels/step 37% and SimParams.macro_k now
# cuts kernels/EVENT ~K-fold by retiring K events per dispatched program
# (sim/simulator.py macro_step).  This ladder measures both halves of that
# claim per K rung: wall-clock ev/s of the timed chunk runs, and the
# kernel-census fusions-per-event of the dispatched macro-step program.
# One subprocess per rung (the fleet-ladder protocol: compile-heavy rungs
# stay isolated and the persistent cache warms per shape).  CPU-proxy
# caveat: on host the step is NOT dispatch-bound, so ev/s moves little —
# the fusion census is the metric that transfers to chip; the on-chip
# ev/s re-measure is on the ROADMAP tunnel checklist.
# ---------------------------------------------------------------------------


def _macro_child() -> dict:
    """One K rung (timed run + optional fusion census, own process)."""
    import numpy as np
    from librabft_simulator_tpu.core.types import SimParams
    from librabft_simulator_tpu.sim import simulator
    from librabft_simulator_tpu.sim.simulator import dedupe_buffers
    from librabft_simulator_tpu.utils.xops import _bool_env

    k = int(os.environ["BENCH_MACRO_CHILD"])
    batch = int(os.environ.get("BENCH_B", 2048))
    n_nodes = int(os.environ.get("BENCH_NODES", 4))
    reps = int(os.environ.get("BENCH_REPS", 2))
    # Events per timed dispatch stay constant across rungs (outer scan
    # length shrinks as K grows), so rung times are comparable — the
    # parent already raised BENCH_STEPS to cover the largest K, and a
    # K that doesn't divide it rounds the dispatch UP to whole
    # macro-steps (events_per_dispatch records the truth either way).
    events = int(os.environ.get("BENCH_STEPS", 32))
    outer = max(-(-events // k), 1)
    p = SimParams(n_nodes=n_nodes, delay_kind="uniform",
                  queue_cap=max(32, 4 * n_nodes), epoch_handoff=False,
                  max_clock=2**30, macro_k=k)
    st = dedupe_buffers(simulator.init_batch(
        p, np.arange(batch, dtype=np.uint32)))
    run = simulator.make_run_fn(p, outer)
    from librabft_simulator_tpu.telemetry import ledger as tledger

    lg = tledger.get()
    with lg.span(tledger.DISPATCH, what="macro_warmup", k=k) as sp_c:
        st = run(st)
        jax.block_until_ready(st)
    compile_s = sp_c.dur_s
    e0 = int(np.sum(jax.device_get(st.n_events)))
    r0 = _fleet_rounds(st.store.current_round)
    with lg.span(tledger.RUN, what="macro_timed", k=k, reps=reps) as sp_t:
        for _ in range(reps):
            st = run(st)
        jax.block_until_ready(st)
    dt = sp_t.dur_s
    e1 = int(np.sum(jax.device_get(st.n_events)))
    r1 = _fleet_rounds(st.store.current_round)
    row = {
        "k": k, "instances": batch, "n_nodes": n_nodes,
        "outer_steps": outer, "events_per_dispatch": outer * k,
        "events_per_sec": round((e1 - e0) / dt, 1),
        "rounds_per_sec": round((r1 - r0) / dt, 1),
        "elapsed_s": round(dt, 3), "compile_s": round(compile_s, 1),
    }
    census_on = _bool_env("BENCH_MACRO_CENSUS")
    if census_on is None or census_on:
        # The dispatched macro-step program's fusion count, from the same
        # census implementation CI gates (scripts/kernel_census.py): this
        # is the metric that transfers to the chip's dispatch queue — so
        # it censuses the TPU-SHAPE lowering forms explicitly (packed
        # planes + dense writes + gated handlers, exactly the
        # kernel_census tpu_shape_k* modes), while the timed ev/s above
        # ran whatever forms the host backend resolves.
        import dataclasses

        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "scripts"))
        import kernel_census

        # Single-sourced from the census's own mode table, so the ladder
        # and the CI gate can never census different graphs.
        p_census = dataclasses.replace(
            p, **kernel_census.MODES["tpu_shape"], macro_k=k)
        c = kernel_census.census_step(p_census, batch)
        row["top_fusions"] = c["top_fusions"]
        row["fusions_per_event"] = c["fusions_per_event"]
        row["whiles"] = c["whiles"]
    return row


def run_macro_ladder(out_path: str) -> dict:
    """Drive one subprocess per K rung; collect the ladder artifact."""
    try:
        rungs = [int(x) for x in
                 os.environ.get("BENCH_MACRO_KS", "1,4,16,64").split(",")
                 if x.strip()]
    except ValueError:
        print("bench: ignoring malformed BENCH_MACRO_KS", file=sys.stderr)
        rungs = [1, 4, 16, 64]
    # Equal events per timed dispatch on EVERY rung (else a K above
    # BENCH_STEPS would time bigger dispatches than the K=1 baseline and
    # bias the speedup curve at exactly the rung that matters most):
    # raise the per-dispatch event count to cover the largest K.
    events = max(int(os.environ.get("BENCH_STEPS", 32)), max(rungs, default=1))
    rows, failures = [], {}
    for k in rungs:
        env = dict(os.environ, BENCH_PLATFORM="cpu",
                   BENCH_MACRO_CHILD=str(k), BENCH_STEPS=str(events))
        env.pop("BENCH_MACRO", None)
        r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                           env=env, capture_output=True, text=True)
        line = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
        try:
            row = json.loads(line)
        except ValueError:
            failures[k] = f"rc={r.returncode}: {(r.stderr or line)[-300:]}"
            print(f"bench: macro rung k={k} failed ({failures[k][:120]})",
                  file=sys.stderr)
            continue
        rows.append(row)
        print(json.dumps(row), file=sys.stderr, flush=True)
    base_ev = next((r["events_per_sec"] for r in rows if r["k"] == 1), None)
    base_fus = next((r.get("fusions_per_event") for r in rows
                     if r["k"] == 1), None)
    for r in rows:
        r["ev_speedup_vs_k1"] = (round(r["events_per_sec"] / base_ev, 3)
                                 if base_ev else None)
        r["fusion_amortization_vs_k1"] = (
            round(base_fus / r["fusions_per_event"], 1)
            if base_fus and r.get("fusions_per_event") else None)
    out = {
        "kind": "macro_ladder",
        "platform": "cpu",
        "emulated": True,
        "note": "CPU-lowering proxy: fusions_per_event is the census of "
                "the dispatched macro-step program (the metric that "
                "transfers to the chip's per-kernel dispatch cost); host "
                "ev/s is NOT dispatch-bound so it moves little here — "
                "the on-chip ev/s rung is on the ROADMAP tunnel "
                "checklist",
        "rungs": rows,
        "failures": failures,
    }
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    # Headline = the MEASURED quantity (ev/s vs the K=1 rung at equal
    # events per dispatch).  The fusions-per-event amortization rides
    # along as a curve, not the headline: for the rolled inner scan it
    # is ~K by construction (a static program-shape property — the
    # census acceptance metric, meaningful as a dispatch-cost proxy
    # only on chip), so printing it as "value" would report the
    # configuration, not a measurement.  Rows carry None curve entries
    # when the census was skipped (BENCH_MACRO_CENSUS=0) or the k=1
    # baseline failed — null, never a fake 0 or an arbitrary rung.
    cands = [r for r in rows if r.get("ev_speedup_vs_k1")]
    best = max(cands, key=lambda r: r["ev_speedup_vs_k1"]) \
        if cands else None
    head = {
        "metric": "macro_ev_speedup_vs_k1",
        "value": best["ev_speedup_vs_k1"] if best else None,
        "unit": "x ev/s vs k=1 at equal events/dispatch "
                "(host proxy; on-chip rung on the tunnel checklist)",
        "k": best["k"] if best else None,
        "ev_speedup_curve": {str(r["k"]): r["ev_speedup_vs_k1"]
                             for r in rows},
        "fusion_amortization_curve": {
            str(r["k"]): r.get("fusion_amortization_vs_k1")
            for r in rows},
        "artifact": out_path,
    }
    print(json.dumps(head))
    return out


# ---------------------------------------------------------------------------
# Device-dispatch ring ladder (BENCH_RING=1): host-vs-device A/B per depth.
#
# The double-buffered host loop pays one dispatch + one [D]-digest poll per
# chunk; SimParams.wrap="device" (parallel/sharded.py) retires up to ring_k
# chunks inside ONE dispatched outer program and egresses a [ring_k, 13]
# digest ring once per outer call — polls-per-retired-chunk drops to 1/K on
# non-halting horizons.  This ladder measures that claim per ring depth K,
# with a wrap="host" A/B leg per rung (identical shape/steps), and lands
# ttfc (admission-to-first-chunk, cold compile included) at each depth —
# the admission-latency side of the ring tradeoff.  One subprocess per leg
# (the fleet-ladder protocol).  CPU-proxy caveat: host polls are cheap
# here; the poll-count collapse is the metric that transfers to the chip's
# dispatch queue (on-chip rung on the ROADMAP tunnel checklist).
# ---------------------------------------------------------------------------


def _ring_child() -> dict:
    """One ring-ladder leg (own process): cold run for ttfc, then a timed
    run, both through the production ``run_sharded`` dispatch loop."""
    import numpy as np
    from librabft_simulator_tpu.core.types import SimParams
    from librabft_simulator_tpu.parallel import mesh as mesh_ops
    from librabft_simulator_tpu.parallel import sharded
    from librabft_simulator_tpu.sim import parallel_sim, simulator
    from librabft_simulator_tpu.sim.simulator import dedupe_buffers
    from librabft_simulator_tpu.telemetry import ledger as tledger

    cfg = json.loads(os.environ["BENCH_RING_CHILD"])
    k, wrap, dp = int(cfg["k"]), cfg["wrap"], int(cfg["dp"])
    engine_name = cfg.get("engine", "serial")
    engine = parallel_sim if engine_name == "parallel" else simulator
    b_per = int(os.environ.get("BENCH_RING_B", 64))
    chunk = int(os.environ.get("BENCH_RING_STEPS", 8))
    chunks = int(os.environ.get("BENCH_RING_CHUNKS", 64))
    n_nodes = int(os.environ.get("BENCH_NODES", 4))
    batch = b_per * dp
    p = SimParams(n_nodes=n_nodes, delay_kind="uniform",
                  queue_cap=max(32, 4 * n_nodes), epoch_handoff=False,
                  max_clock=2**30, wrap=wrap,
                  **({"ring_k": k} if wrap == "device" else {}))
    mesh = mesh_ops.make_mesh(n_dp=dp, n_mp=1, devices=jax.devices()[:dp])
    st = dedupe_buffers(engine.init_batch(p, sharded.fleet_seeds(0, batch)))
    lg = tledger.get()
    # Cold leg: one chunk end-to-end — ttfc is admission-to-first-chunk
    # at this ring depth, XLA compile included (the admission-boundary
    # latency a serve operator pays after arming LIBRABFT_SERVE_RING_K).
    st = sharded.run_sharded(p, mesh, st, num_steps=chunk, chunk=chunk,
                             engine=engine)
    cold = lg.pipeline_stats()
    e0 = int(np.sum(jax.device_get(st.n_events)))
    t0 = time.perf_counter()
    st = sharded.run_sharded(p, mesh, st, num_steps=chunk * chunks,
                             chunk=chunk, engine=engine)
    jax.block_until_ready(jax.tree_util.tree_leaves(st)[0])
    dt = time.perf_counter() - t0
    e1 = int(np.sum(jax.device_get(st.n_events)))
    pipe = lg.pipeline_stats()
    ring = lg.ring_stats()
    row = {
        "k": k, "wrap": wrap, "dp": dp, "engine": engine_name,
        "instances": batch, "chunk_steps": chunk, "chunks": chunks,
        "events_per_sec": round((e1 - e0) / dt, 1),
        "elapsed_s": round(dt, 3),
        "time_to_first_chunk_s": cold.get("time_to_first_chunk_s"),
        # Host wrap: one outer call (dispatch+poll) per chunk.
        "dispatches": ring["dispatches"] if ring else pipe["chunks"],
        # Host wrap: one poll per retired chunk by construction.
        "polls_per_retired_chunk": (
            ring["polls_per_retired_chunk"] if ring else 1.0),
        "retired_per_dispatch": (
            ring["retired_per_dispatch"] if ring else 1.0),
        "ring_full": ring["ring_full"] if ring else None,
        "early_exit": ring["early_exit"] if ring else None,
    }
    return row


def run_ring_ladder(out_path: str) -> dict:
    """Drive one subprocess per (K, wrap) leg; write RUNTIME_LEDGER_r14."""
    from librabft_simulator_tpu.telemetry import ledger as tledger

    try:
        depths = [int(x) for x in
                  os.environ.get("BENCH_RING_KS", "1,4,16,64").split(",")
                  if x.strip()]
    except ValueError:
        print("bench: ignoring malformed BENCH_RING_KS", file=sys.stderr)
        depths = [1, 4, 16, 64]
    base_flags = " ".join(
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)

    def run_child(cfg: dict):
        # LIBRABFT_AOT=0 + LIBRABFT_COMPILE_CACHE=0: the round-13 store
        # and the shared /tmp/jax_cache would warm whichever legs happen
        # to share a cached executable (the host twin's program is
        # K-independent), skewing the cross-depth ttfc comparison — every
        # leg pays its own uniform cold compile instead.
        env = dict(os.environ, BENCH_PLATFORM="cpu", LIBRABFT_AOT="0",
                   LIBRABFT_COMPILE_CACHE="0",
                   BENCH_RING_CHILD=json.dumps(cfg),
                   XLA_FLAGS=(base_flags +
                              " --xla_force_host_platform_device_count="
                              f"{max(cfg['dp'], 1)}").strip())
        env.pop("BENCH_RING", None)
        r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                           env=env, capture_output=True, text=True)
        line = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
        try:
            return json.loads(line), None
        except ValueError:
            return None, f"rc={r.returncode}: {(r.stderr or line)[-300:]}"

    # Per depth: a device leg and its host A/B twin (identical shape and
    # step budget — only the dispatch wrap differs), dp=1; plus one
    # 2-shard device/host pair at the middle depth (the sharded leg of
    # the bit-identity acceptance tests, measured too).
    legs = []
    for k in depths:
        legs += [dict(k=k, wrap="device", dp=1),
                 dict(k=k, wrap="host", dp=1)]
    mid = depths[len(depths) // 2] if depths else 4
    legs += [dict(k=mid, wrap="device", dp=2),
             dict(k=mid, wrap="host", dp=2)]
    rows, failures = [], {}
    for cfg in legs:
        row, err = run_child(cfg)
        if row is None:
            failures[f"k{cfg['k']}_{cfg['wrap']}_dp{cfg['dp']}"] = err
            print(f"bench: ring leg {cfg} failed ({err[:120]})",
                  file=sys.stderr)
            continue
        rows.append(row)
        print(json.dumps(row), file=sys.stderr, flush=True)
    host_ttfc = next((r["time_to_first_chunk_s"] for r in rows
                      if r["wrap"] == "host" and r["dp"] == 1), None)
    for r in rows:
        r["ttfc_vs_host_s"] = (
            round(r["time_to_first_chunk_s"] - host_ttfc, 3)
            if host_ttfc is not None
            and r["time_to_first_chunk_s"] is not None else None)
    art = {
        "kind": "runtime_ledger",
        "flavor": "ring_dispatch",
        "ledger_version": tledger.LEDGER_VERSION,
        "platform": "cpu",
        "emulated": True,
        "time_to_first_chunk_s": host_ttfc,
        "note": "device-dispatch ring ladder (SimParams.wrap='device'): "
                "per ring depth K, a device leg and a wrap='host' A/B "
                "twin at identical shape/steps.  "
                "polls_per_retired_chunk = host digest fetches per "
                "retired chunk (1/K target on non-halting horizons; "
                "1.0 on the host wrap by construction); "
                "time_to_first_chunk_s = admission to the first chunk "
                "digest on host, cold XLA compile included (AOT store "
                "and persistent compile cache disarmed in ladder "
                "children so every leg is uniformly cold) — the "
                "admission-boundary latency a ring-armed serve session "
                "pays (LIBRABFT_SERVE_RING_K); ttfc_vs_host_s = that "
                "minus the dp=1 host leg.  CPU-lowering proxy: the "
                "poll-count collapse is the metric that transfers to "
                "chip; on-chip rung on the ROADMAP tunnel checklist.",
        "rungs": rows,
        "failures": failures,
    }
    with open(out_path, "w") as f:
        json.dump(art, f, indent=1)
    print(f"bench: wrote ring-ladder artifact {out_path}", file=sys.stderr)
    dev = [r for r in rows if r["wrap"] == "device" and r["dp"] == 1]
    best = min(dev, key=lambda r: r["polls_per_retired_chunk"]) \
        if dev else None
    head = {
        "metric": "ring_polls_per_retired_chunk",
        "value": best["polls_per_retired_chunk"] if best else None,
        "unit": "host polls per retired chunk (device wrap, dp=1)",
        "k": best["k"] if best else None,
        "poll_curve": {f"k{r['k']}": r["polls_per_retired_chunk"]
                       for r in dev},
        "ttfc_curve_s": {f"k{r['k']}": r["time_to_first_chunk_s"]
                         for r in dev},
        "host_ttfc_s": host_ttfc,
        "artifact": out_path,
    }
    print(json.dumps(head))
    return art


def main():
    if os.environ.get("BENCH_RING_CHILD"):
        print(json.dumps(_ring_child()))
        return
    if os.environ.get("BENCH_RING"):
        art = run_ring_ladder(os.environ.get("BENCH_RING_OUT",
                                             "RUNTIME_LEDGER_r14.json"))
        # A ladder with missing legs is a broken A/B, not a success.
        if art["failures"] or not art["rungs"]:
            sys.exit(1)
        return
    if os.environ.get("BENCH_POD"):
        # The multi-process pod ladder (scripts/fleet_pod.py): each rung
        # is its own jax.distributed job, so the harness runs in a fresh
        # parent process that never attached a backend of its own.
        r = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "scripts", "fleet_pod.py")])
        sys.exit(r.returncode)
    if os.environ.get("BENCH_MACRO_CHILD"):
        print(json.dumps(_macro_child()))
        return
    if os.environ.get("BENCH_MACRO"):
        out = run_macro_ladder(os.environ.get("BENCH_MACRO_OUT",
                                              "BENCH_MACRO_r11.json"))
        # A ladder with missing rungs is a broken curve, not a success.
        if out["failures"] or not out["rungs"]:
            sys.exit(1)
        return
    if os.environ.get("BENCH_FLEET_CHILD"):
        print(json.dumps(_fleet_child()))
        return
    if os.environ.get("BENCH_FLEET"):
        out = run_fleet_ladder(os.environ.get("BENCH_FLEET_OUT",
                                              "MULTICHIP_FLEET_r08.json"))
        # A ladder with missing rungs is a broken scaling curve, not a
        # success: fail loud so CI / warm_cache consumers see it.
        if out["failures"] or not out["rungs"]:
            sys.exit(1)
        return
    if os.environ.get("BENCH_SWEEP"):
        run_sweep(os.environ.get("BENCH_SWEEP_OUT", "BENCH_SWEEP.json"))
        return
    try:
        out = run_all()
    except Exception as e:  # noqa: BLE001 - contract line must still print
        import traceback

        traceback.print_exc()
        if _PLATFORM != "cpu":
            # Retry once on the always-available backend.
            print(f"bench: {_PLATFORM} run failed ({type(e).__name__}); "
                  "re-running on cpu", file=sys.stderr)
            _PROBE_DIAG["error"] = f"{_PLATFORM} run failed: " \
                f"{type(e).__name__}: {e}"[:300]
            env = dict(os.environ, BENCH_PLATFORM="cpu",
                       BENCH_PROBE_DIAG=json.dumps(_PROBE_DIAG))
            r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                               env=env)
            sys.exit(r.returncode)
        out = {
            "metric": "rounds_per_sec", "value": 0.0, "unit": "rounds/sec",
            "vs_baseline": 0.0, "platform": "none",
            "error": f"{type(e).__name__}: {e}"[:300],
            "probe": _PROBE_DIAG,
        }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
